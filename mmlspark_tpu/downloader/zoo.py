"""Model zoo — the ModelDownloader analogue.

The reference maintains a repository of CNTK model schemas (uri, hash,
size, inputNode, layerNames) fetched over HDFS/HTTP
(downloader/ModelDownloader.scala:27-118, downloader/Schema.scala:54-66).
Here the repository is a local directory of Flax checkpoints + JSON
schemas. TRAINED weights ship inside the package for the compact backbones
(``downloader/builtin/``, produced by tools/train_zoo_backbone.py from the
committed datasets — the egress-free stand-in for the reference's remote
model files). Remote URIs can be registered via RemoteRepository; absent
large-model checkpoints fall back to seeded random inits with a loud
warning (weights are still content-hashed so cache hits are exact).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
from dataclasses import asdict, dataclass, field
from typing import Any, Optional

import numpy as np

from mmlspark_tpu.core.utils import retry_with_backoff

log = logging.getLogger("mmlspark_tpu.downloader")

DEFAULT_REPO = os.path.join(
    os.environ.get("MMLSPARK_TPU_HOME", os.path.expanduser("~/.mmlspark_tpu")), "models"
)

# Trained checkpoints shipped INSIDE the package (tools/train_zoo_backbone.py
# trains them from the committed datasets): the egress-free counterpart of
# the reference's remote model repository (ModelDownloader.scala:210-276).
PACKAGED_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "builtin")


@dataclass
class ModelSchema:
    """Metadata for one zoo model (downloader/Schema.scala:54-66 analogue)."""

    name: str
    variant: str = "ResNet50"
    num_classes: int = 1000
    image_size: int = 224
    small_inputs: bool = False
    input_node: str = "image"
    layer_names: list = field(
        default_factory=lambda: [
            "logits", "pool", "layer4", "layer3", "layer2", "layer1", "stem",
        ]
    )
    uri: Optional[str] = None
    sha256: Optional[str] = None
    seed: int = 0
    # torch-exact strided padding: set for torchvision-imported weights so
    # the flax module reproduces torchvision feature maps (torch_import.py)
    torch_padding: bool = False
    # backbone width (ResNet num_filters); None = the variant's default
    # (compact zoo entries train thinner)
    num_filters: Optional[int] = None

    def to_json(self) -> str:
        return json.dumps(asdict(self), indent=1)


# ViT output-layer order; must match models/vit.py ViT.LAYER_NAMES (a
# top-level import would be circular through the models package init —
# tests/test_vit.py::test_layer_names_match_zoo_schema pins the equality)
_VIT_LAYERS = ("logits", "pool", "encoder", "patches")

BUILTIN_MODELS = {
    "ResNet18": ModelSchema(name="ResNet18", variant="ResNet18"),
    "ResNet34": ModelSchema(name="ResNet34", variant="ResNet34"),
    "ResNet50": ModelSchema(name="ResNet50", variant="ResNet50"),
    "ResNet101": ModelSchema(name="ResNet101", variant="ResNet101"),
    "ResNet50_ImageNet_CIFAR": ModelSchema(
        name="ResNet50_ImageNet_CIFAR",
        variant="ResNet50",
        num_classes=10,
        image_size=32,
        small_inputs=True,
    ),
    "ViTB16": ModelSchema(
        name="ViTB16",
        variant="ViTB16",
        layer_names=list(_VIT_LAYERS),
    ),
    "ViTTiny": ModelSchema(
        name="ViTTiny",
        variant="ViTTiny",
        num_classes=10,
        image_size=32,
        layer_names=list(_VIT_LAYERS),
    ),
}


class ModelDownloader:
    """Local/remote model repository client."""

    def __init__(self, repo_dir: str = DEFAULT_REPO):
        self.repo_dir = repo_dir
        os.makedirs(repo_dir, exist_ok=True)

    def list_models(self) -> list:
        names = set(BUILTIN_MODELS)
        dirs = [self.repo_dir]
        if os.path.isdir(PACKAGED_DIR):
            dirs.append(PACKAGED_DIR)
        for d in dirs:
            for f in os.listdir(d):
                if f.endswith(".schema.json"):
                    names.add(f[: -len(".schema.json")])
        return sorted(names)

    def _paths(self, name: str) -> tuple:
        return (
            os.path.join(self.repo_dir, f"{name}.schema.json"),
            os.path.join(self.repo_dir, f"{name}.msgpack"),
        )

    def install_blob(self, schema: ModelSchema, blob: bytes) -> ModelSchema:
        """Write a serialized-weights blob + schema into the repo (single
        place that knows the on-disk layout); fills sha256 if absent."""
        if not schema.sha256:
            schema.sha256 = hashlib.sha256(blob).hexdigest()
        spath, wpath = self._paths(schema.name)
        with open(wpath, "wb") as f:
            f.write(blob)
        with open(spath, "w") as f:
            f.write(schema.to_json())
        return schema

    def register(self, schema: ModelSchema, variables: Any) -> None:
        """Install a model (e.g. converted pretrained weights) into the repo."""
        from flax import serialization as fser

        blob = fser.msgpack_serialize(_to_np(variables))
        schema.sha256 = hashlib.sha256(blob).hexdigest()
        self.install_blob(schema, blob)

    def download_by_name(self, name: str) -> ModelSchema:
        """Ensure the named model exists locally; return its schema."""
        spath, wpath = self._paths(name)
        pk_s = os.path.join(PACKAGED_DIR, f"{name}.schema.json")
        pk_w = os.path.join(PACKAGED_DIR, f"{name}.msgpack")
        packaged = os.path.exists(pk_s) and os.path.exists(pk_w)
        if os.path.exists(spath) and os.path.exists(wpath):
            with open(spath) as f:
                local = ModelSchema(**json.load(f))
            if packaged:
                # a retrained packaged checkpoint supersedes a stale local
                # install (compare by recorded sha256)
                with open(pk_s) as f:
                    pk_schema = ModelSchema(**json.load(f))
                if pk_schema.sha256 and pk_schema.sha256 != local.sha256:
                    log.info("reinstalling %s from updated packaged weights", name)
                else:
                    return local
            else:
                return local
        if packaged:
            # packaged trained checkpoint: install into the local repo verbatim
            with open(pk_s) as f:
                schema = ModelSchema(**json.load(f))
            with open(pk_w, "rb") as f:
                blob = f.read()
            if schema.sha256 and hashlib.sha256(blob).hexdigest() != schema.sha256:
                raise IOError(f"packaged checksum mismatch for model {name}")
            return self.install_blob(schema, blob)
        schema = BUILTIN_MODELS.get(name)
        if schema is None:
            raise KeyError(f"unknown model {name!r}; known: {self.list_models()}")
        if schema.uri:  # remote fetch path (with retries); unused offline
            retry_with_backoff(lambda: self._fetch(schema, wpath))
            with open(wpath, "rb") as f:
                blob = f.read()
            schema.sha256 = hashlib.sha256(blob).hexdigest()
            self.install_blob(schema, blob)
        else:
            from mmlspark_tpu.models.resnet import RESNETS, init_resnet

            log.warning(
                "model %r has no trained checkpoint in this egress-free "
                "repository; materializing a SEEDED RANDOM init — features "
                "will carry no semantic content (use ResNet8_Digits for "
                "trained weights, or RemoteRepository.sync to import real "
                "checkpoints)",
                name,
            )
            if schema.variant in RESNETS:
                width = {} if schema.num_filters is None else {
                    "num_filters": schema.num_filters
                }
                _, variables = init_resnet(
                    schema.variant,
                    num_classes=schema.num_classes,
                    image_size=schema.image_size,
                    small_inputs=schema.small_inputs,
                    seed=schema.seed,
                    **width,
                )
            else:
                from mmlspark_tpu.models.vit import init_vit

                _, variables = init_vit(
                    schema.variant,
                    num_classes=schema.num_classes,
                    image_size=schema.image_size,
                    seed=schema.seed,
                )
            self.register(schema, variables)
        return schema

    def load(self, name: str) -> tuple:
        """Return (module, variables, schema) ready for XLAModel."""
        from flax import serialization as fser

        from mmlspark_tpu.models.resnet import RESNETS

        schema = self.download_by_name(name)
        _, wpath = self._paths(name)
        with open(wpath, "rb") as f:
            blob = f.read()
        if schema.sha256 and hashlib.sha256(blob).hexdigest() != schema.sha256:
            raise IOError(f"checksum mismatch for model {name}")
        variables = fser.msgpack_restore(blob)
        # checkpoints may be stored float16 (half the repo weight); compute
        # always runs f32/bf16
        import jax as _jax
        import numpy as _np

        variables = _jax.tree_util.tree_map(
            lambda a: a.astype(_np.float32)
            if getattr(a, "dtype", None) == _np.float16 else a,
            variables,
        )
        if schema.variant in RESNETS:
            width = {} if schema.num_filters is None else {
                "num_filters": schema.num_filters
            }
            module = RESNETS[schema.variant](
                num_classes=schema.num_classes,
                small_inputs=schema.small_inputs,
                torch_padding=schema.torch_padding, **width,
            )
        else:
            from mmlspark_tpu.models.vit import VITS

            module = VITS[schema.variant](num_classes=schema.num_classes)
        return module, variables, schema

    def _fetch(self, schema: ModelSchema, wpath: str) -> None:
        import urllib.request

        urllib.request.urlretrieve(schema.uri, wpath)  # noqa: S310


class RemoteRepository:
    """HTTP model repository (the remote ``Repository[ModelSchema]`` of
    ModelDownloader.scala:55-118): a base URL serving ``index.json`` (list
    of schema dicts) and one ``<name>.msgpack`` weight blob per model.
    ``sync`` mirrors models into a local ModelDownloader repo, verifying
    checksums, with retry/backoff (FaultToleranceUtils analogue)."""

    _NAME_OK = re.compile(r"[A-Za-z0-9._-]+")

    def __init__(
        self,
        base_url: str,
        local: Optional[ModelDownloader] = None,
        timeout_s: float = 60.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.local = local or ModelDownloader()
        self.timeout_s = timeout_s

    def _get(self, path: str) -> bytes:
        import urllib.error
        import urllib.request

        def pull() -> bytes:
            # explicit timeout: a stalled server must raise into the backoff
            # schedule, not hang sync() (retryWithTimeout semantics)
            with urllib.request.urlopen(  # noqa: S310
                f"{self.base_url}/{path}", timeout=self.timeout_s
            ) as r:
                return r.read()

        def retryable(e: Exception) -> bool:
            # 4xx can never succeed on retry; everything else (5xx, network)
            # gets the backoff schedule
            return not (
                isinstance(e, urllib.error.HTTPError) and 400 <= e.code < 500
            )

        return retry_with_backoff(pull, retryable=retryable)

    def list_models(self) -> list:
        index = json.loads(self._get("index.json"))
        return [ModelSchema(**s) for s in index]

    def _checked_name(self, name: str) -> str:
        # remote-controlled names become local file paths: allow only plain
        # identifiers so a hostile index cannot traverse out of repo_dir
        if not self._NAME_OK.fullmatch(name) or ".." in name:
            raise ValueError(f"illegal remote model name {name!r}")
        return name

    def download(self, schema: ModelSchema) -> ModelSchema:
        """Fetch one model's weights into the local repo."""
        name = self._checked_name(schema.name)
        blob = self._get(f"{name}.msgpack")
        if schema.sha256 and hashlib.sha256(blob).hexdigest() != schema.sha256:
            raise IOError(f"checksum mismatch downloading {name}")
        return self.local.install_blob(schema, blob)

    def download_by_name(self, name: str) -> ModelSchema:
        """Fetch schema + weights into the local repo; returns the schema."""
        schema = next((s for s in self.list_models() if s.name == name), None)
        if schema is None:
            raise KeyError(f"model {name!r} not in remote index")
        return self.download(schema)

    def sync(self) -> list:
        """Mirror every remote model locally; returns the schemas.
        The index is fetched once (not per model)."""
        return [self.download(s) for s in self.list_models()]


def _to_np(tree: Any) -> Any:
    if isinstance(tree, dict):
        return {k: _to_np(v) for k, v in tree.items()}
    return np.asarray(tree)
