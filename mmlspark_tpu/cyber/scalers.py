"""Per-partition score scalers (reference: cyber/utils/scalers.py, 325 LoC).

``StandardScalarScaler``: per-tenant z-score of a value column (fit mean/std
per tenant). ``LinearScalarScaler``: per-tenant affine map of the observed
value range onto [min_required, max_required]. Both are Estimator->Model
pairs keyed by a partition (tenant) column, exactly like the reference.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import ComplexParam, HasInputCol, HasOutputCol, Param
from mmlspark_tpu.core.pipeline import Estimator, Model

# dict key for the no-tenant (global) group; msgpack map keys cannot be None
_GLOBAL = "__global__"


class _ScalerParams(HasInputCol, HasOutputCol):
    partition_key = Param("tenant/partition column; None = global", default=None)

    def __init__(self, **kw: Any):
        super().__init__(**kw)
        if "output_col" not in self._paramMap and "input_col" in self._paramMap:
            self.set(output_col=self._paramMap["input_col"] + "_scaled")

    def _groups(self, df: DataFrame) -> dict:
        vals = np.asarray(df[self.get_or_fail("input_col")], np.float64)
        pk = self.get("partition_key")
        if pk is None:
            return {_GLOBAL: vals}
        keys = df[pk]
        out: dict = {}
        for k in np.unique(keys):
            out[k] = vals[keys == k]
        return out


class StandardScalarScaler(Estimator, _ScalerParams):
    use_std = Param("divide by std (else just center)", default=True, type_=bool)

    def fit(self, df: DataFrame) -> "StandardScalarScalerModel":
        stats = {
            k: (float(v.mean()), float(v.std()) if len(v) > 1 else 1.0)
            for k, v in self._groups(df).items()
        }
        m = StandardScalarScalerModel(**{k: v for k, v in self._paramMap.items()})
        m.set(per_group_stats=stats)
        return m


class StandardScalarScalerModel(Model, _ScalerParams):
    use_std = Param("divide by std (else just center)", default=True, type_=bool)
    per_group_stats = ComplexParam("{tenant: (mean, std)}")

    def transform(self, df: DataFrame) -> DataFrame:
        stats = self.get_or_fail("per_group_stats")
        pk = self.get("partition_key")
        ic, oc = self.get_or_fail("input_col"), self.get("output_col")

        def fn(p: dict) -> dict:
            vals = np.asarray(p[ic], np.float64)
            out = np.zeros_like(vals)
            keys = p[pk] if pk is not None else np.array([_GLOBAL] * len(vals), dtype=object)
            for k in set(keys.tolist()) if len(vals) else set():
                mean, std = stats.get(k, (0.0, 1.0))
                sel = keys == k if pk is not None else slice(None)
                denom = std if (self.get("use_std") and std > 0) else 1.0
                out[sel] = (vals[sel] - mean) / denom
            q = dict(p)
            q[oc] = out
            return q

        return df.map_partitions(fn)


class LinearScalarScaler(Estimator, _ScalerParams):
    min_required_value = Param("target range min", default=0.0, type_=float)
    max_required_value = Param("target range max", default=1.0, type_=float)

    def fit(self, df: DataFrame) -> "LinearScalarScalerModel":
        stats = {
            k: (float(v.min()) if len(v) else 0.0, float(v.max()) if len(v) else 1.0)
            for k, v in self._groups(df).items()
        }
        m = LinearScalarScalerModel(**{k: v for k, v in self._paramMap.items()})
        m.set(per_group_range=stats)
        return m


class LinearScalarScalerModel(Model, _ScalerParams):
    min_required_value = Param("target range min", default=0.0, type_=float)
    max_required_value = Param("target range max", default=1.0, type_=float)
    per_group_range = ComplexParam("{tenant: (min, max)}")

    def transform(self, df: DataFrame) -> DataFrame:
        stats = self.get_or_fail("per_group_range")
        pk = self.get("partition_key")
        ic, oc = self.get_or_fail("input_col"), self.get("output_col")
        lo_t, hi_t = self.get("min_required_value"), self.get("max_required_value")

        def fn(p: dict) -> dict:
            vals = np.asarray(p[ic], np.float64)
            out = np.zeros_like(vals)
            keys = p[pk] if pk is not None else np.array([_GLOBAL] * len(vals), dtype=object)
            for k in set(keys.tolist()) if len(vals) else set():
                lo, hi = stats.get(k, (0.0, 1.0))
                sel = keys == k if pk is not None else slice(None)
                span = hi - lo
                if span <= 0:
                    out[sel] = (lo_t + hi_t) / 2.0
                else:
                    out[sel] = lo_t + (vals[sel] - lo) * (hi_t - lo_t) / span
            q = dict(p)
            q[oc] = out
            return q

        return df.map_partitions(fn)
