"""Complement sampling (reference: cyber/anomaly/complement_access.py).

For explicit-feedback anomaly training the reference augments observed
accesses with sampled UNSEEN (user, resource) pairs given a low rating, so
the factor model learns to separate seen from unseen. ``complement_sample``
draws uniformly from the complement of the access set without
materializing the full U×I grid.
"""

from __future__ import annotations

from typing import Any, Optional

import zlib

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Transformer


def complement_sample(
    users: np.ndarray,
    items: np.ndarray,
    n_users: int,
    n_items: int,
    factor: float = 2.0,
    seed: int = 0,
    user_pool: Optional[np.ndarray] = None,
    item_pool: Optional[np.ndarray] = None,
) -> tuple:
    """Sample ~factor * len(users) (u, i) pairs NOT present in the input set.

    The sampling universe is ``user_pool × item_pool`` when given (use the
    tenant's own observed entities for globally-indexed multi-tenant data),
    else ``range(n_users) × range(n_items)``.
    """
    upool = np.asarray(user_pool if user_pool is not None else np.arange(n_users), np.int64)
    ipool = np.asarray(item_pool if item_pool is not None else np.arange(n_items), np.int64)
    seen = set(zip(users.tolist(), items.tolist()))
    target = int(factor * len(users))
    total_free = len(upool) * len(ipool) - len(seen)
    target = min(target, max(total_free, 0))
    rng = np.random.RandomState(seed)
    out_u, out_i = [], []
    picked: set = set()
    # rejection sampling; dense fallback when the complement is tiny
    attempts = 0
    while len(out_u) < target and attempts < 50 * max(target, 1):
        u = int(upool[rng.randint(0, len(upool))])
        i = int(ipool[rng.randint(0, len(ipool))])
        attempts += 1
        if (u, i) in seen or (u, i) in picked:
            continue
        picked.add((u, i))
        out_u.append(u)
        out_i.append(i)
    if len(out_u) < target:  # dense enumeration of what's left
        for u in upool.tolist():
            for i in ipool.tolist():
                if len(out_u) >= target:
                    break
                if (u, i) not in seen and (u, i) not in picked:
                    picked.add((u, i))
                    out_u.append(u)
                    out_i.append(i)
    return np.array(out_u, np.int64), np.array(out_i, np.int64)


class ComplementSampler(Transformer):
    """DataFrame stage: appends complement (user, item) rows with a fixed
    low rating (per tenant when partition_key is set)."""

    partition_key = Param("tenant column; None = single tenant", default=None)
    user_col = Param("indexed user column", default="user_idx")
    item_col = Param("indexed resource column", default="res_idx")
    rating_col = Param("rating column", default="rating")
    complement_rating = Param("rating for sampled complement rows", default=0.0, type_=float)
    factor = Param("complement rows per observed row", default=2.0, type_=float)
    seed = Param("rng seed", default=0, type_=int)

    def transform(self, df: DataFrame) -> DataFrame:
        uc, ic, rc = self.get("user_col"), self.get("item_col"), self.get("rating_col")
        pk = self.get("partition_key")
        data = df.to_dict()
        users = np.asarray(data[uc], np.int64)
        items = np.asarray(data[ic], np.int64)
        tenants = data[pk] if pk is not None else np.zeros(len(users), np.int64)

        new_cols: dict = {c: [v] for c, v in data.items()}
        for t in np.unique(tenants):
            sel = tenants == t
            tu, ti = users[sel], items[sel]
            cu, ci = complement_sample(
                tu, ti, 0, 0,
                self.get("factor"),
                # independent draws per tenant
                self.get("seed") + (zlib.crc32(str(t).encode()) % (1 << 20)),
                user_pool=np.unique(tu),
                item_pool=np.unique(ti),
            )
            if not len(cu):
                continue
            add = {
                uc: cu,
                ic: ci,
                rc: np.full(len(cu), self.get("complement_rating"), np.float64),
            }
            if pk is not None:
                add[pk] = np.full(len(cu), t, dtype=np.asarray(tenants).dtype)
            for c in new_cols:
                if c in add:
                    new_cols[c].append(add[c])
                else:  # pad untouched columns with zeros/empties of same dtype
                    proto = np.asarray(data[c])
                    pad = np.zeros(len(cu), dtype=proto.dtype) if proto.dtype != object else np.array([None] * len(cu), dtype=object)
                    new_cols[c].append(pad)
        return DataFrame.from_dict({c: np.concatenate(vs) for c, vs in new_cols.items()})
