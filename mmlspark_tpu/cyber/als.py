"""Alternating least squares on device.

Replaces the reference's Spark ALS dependency (cyber
collaborative_filtering.py uses pyspark.ml.recommendation.ALS). Each
alternating half-step solves U (or I) independent ridge systems
``(Y^T W_u Y + lam I) x_u = Y^T W_u r_u``; they are built with one einsum
and solved as a stacked batch of (F, F) systems — MXU-sized work, no
Python per-user loop. Explicit mode uses the observation mask as weights;
implicit mode (Hu-Koren-Volinsky) uses confidence ``1 + alpha*r`` on all
cells with binary preference targets.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnums=(3, 4, 5, 6))
def _als_run(
    r: jnp.ndarray,
    w: jnp.ndarray,
    key: jnp.ndarray,
    rank: int,
    iters: int,
    reg: float,
    implicit: bool,
) -> tuple:
    u_n, i_n = r.shape
    ku, ki = jax.random.split(key)
    x = 0.1 * jax.random.normal(ku, (u_n, rank), jnp.float32)
    y = 0.1 * jax.random.normal(ki, (i_n, rank), jnp.float32)
    eye = jnp.eye(rank, dtype=jnp.float32) * reg

    if implicit:
        conf = 1.0 + w * r  # w carries alpha; preference is binarized r
        pref = (r > 0).astype(jnp.float32)
        targets, weights = pref, conf
    else:
        targets, weights = r, w

    def solve_side(fixed: jnp.ndarray, t: jnp.ndarray, wt: jnp.ndarray) -> jnp.ndarray:
        # one system per row of t: (F,F) grams stacked then batch-solved
        a = jnp.einsum("if,ui,ig->ufg", fixed, wt, fixed) + eye[None]
        b = jnp.einsum("if,ui,ui->uf", fixed, wt, t)
        return jnp.linalg.solve(a, b[..., None])[..., 0]

    def step(carry, _):
        x, y = carry
        x = solve_side(y, targets, weights)
        y = solve_side(x, targets.T, weights.T)
        return (x, y), None

    (x, y), _ = jax.lax.scan(step, (x, y), None, length=iters)
    return x, y


def als_train(
    ratings: np.ndarray,
    mask: Optional[np.ndarray] = None,
    rank: int = 10,
    iters: int = 10,
    reg: float = 0.1,
    implicit: bool = False,
    alpha: float = 40.0,
    seed: int = 0,
) -> tuple:
    """Train on a dense (U, I) ratings matrix; returns (user_factors, item_factors).

    ``mask``: 1 where observed (defaults to ratings != 0). In implicit mode
    the mask is ignored and confidence = 1 + alpha * ratings everywhere.
    """
    r = jnp.asarray(ratings, jnp.float32)
    if implicit:
        w = jnp.full(r.shape, alpha, jnp.float32)
    else:
        w = jnp.asarray(
            mask if mask is not None else (ratings != 0), jnp.float32
        )
    x, y = _als_run(r, w, jax.random.PRNGKey(seed), rank, iters, reg, implicit)
    return np.asarray(x), np.asarray(y)


def als_predict(user_factors: np.ndarray, item_factors: np.ndarray,
                users: np.ndarray, items: np.ndarray) -> np.ndarray:
    """Pairwise predicted affinity x_u · y_i for aligned index arrays."""
    return np.einsum(
        "nf,nf->n", user_factors[np.asarray(users)], item_factors[np.asarray(items)]
    )
