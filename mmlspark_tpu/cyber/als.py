"""Alternating least squares on device.

Replaces the reference's Spark ALS dependency (cyber
collaborative_filtering.py uses pyspark.ml.recommendation.ALS). Each
alternating half-step solves U (or I) independent ridge systems
``(Y^T W_u Y + lam I) x_u = Y^T W_u r_u``; they are built with one einsum
and solved as a stacked batch of (F, F) systems — MXU-sized work, no
Python per-user loop. Explicit mode uses the observation mask as weights;
implicit mode (Hu-Koren-Volinsky) uses confidence ``1 + alpha*r`` on all
cells with binary preference targets.

Two entry points:
- ``als_train`` — dense (U, I) matrix; fine for per-tenant demo scale.
- ``als_train_coo`` — SPARSE (user, item, rating) triples, the production
  path (Spark ALS also consumes sparse ratings): gram matrices and
  right-hand sides accumulate per-edge via ``segment_sum`` over
  fixed-size edge blocks under ``lax.scan``, so memory is
  O(U*F^2 + block*F^2), never O(U*I).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnums=(3, 4, 5, 6))
def _als_run(
    r: jnp.ndarray,
    w: jnp.ndarray,
    key: jnp.ndarray,
    rank: int,
    iters: int,
    reg: float,
    implicit: bool,
) -> tuple:
    u_n, i_n = r.shape
    ku, ki = jax.random.split(key)
    x = 0.1 * jax.random.normal(ku, (u_n, rank), jnp.float32)
    y = 0.1 * jax.random.normal(ki, (i_n, rank), jnp.float32)
    eye = jnp.eye(rank, dtype=jnp.float32) * reg

    if implicit:
        conf = 1.0 + w * r  # w carries alpha; preference is binarized r
        pref = (r > 0).astype(jnp.float32)
        targets, weights = pref, conf
    else:
        targets, weights = r, w

    def solve_side(fixed: jnp.ndarray, t: jnp.ndarray, wt: jnp.ndarray) -> jnp.ndarray:
        # one system per row of t: (F,F) grams stacked then batch-solved
        a = jnp.einsum("if,ui,ig->ufg", fixed, wt, fixed) + eye[None]
        b = jnp.einsum("if,ui,ui->uf", fixed, wt, t)
        return jnp.linalg.solve(a, b[..., None])[..., 0]

    def step(carry, _):
        x, y = carry
        x = solve_side(y, targets, weights)
        y = solve_side(x, targets.T, weights.T)
        return (x, y), None

    (x, y), _ = jax.lax.scan(step, (x, y), None, length=iters)
    return x, y


def als_train(
    ratings: np.ndarray,
    mask: Optional[np.ndarray] = None,
    rank: int = 10,
    iters: int = 10,
    reg: float = 0.1,
    implicit: bool = False,
    alpha: float = 40.0,
    seed: int = 0,
) -> tuple:
    """Train on a dense (U, I) ratings matrix; returns (user_factors, item_factors).

    ``mask``: 1 where observed (defaults to ratings != 0). In implicit mode
    the mask is ignored and confidence = 1 + alpha * ratings everywhere.
    """
    r = jnp.asarray(ratings, jnp.float32)
    if implicit:
        w = jnp.full(r.shape, alpha, jnp.float32)
    else:
        w = jnp.asarray(
            mask if mask is not None else (ratings != 0), jnp.float32
        )
    x, y = _als_run(r, w, jax.random.PRNGKey(seed), rank, iters, reg, implicit)
    return np.asarray(x), np.asarray(y)


_EDGE_BLOCK = 8192


@partial(jax.jit, static_argnums=(5, 6, 7, 8, 9, 10))
def _als_run_coo(
    eu: jnp.ndarray,       # (E,) int32 user of each edge (padded w/ weight 0)
    ei: jnp.ndarray,       # (E,) int32 item of each edge
    er: jnp.ndarray,       # (E,) float32 rating
    ew: jnp.ndarray,       # (E,) float32 edge weight (0 = padding)
    key: jnp.ndarray,
    u_n: int,
    i_n: int,
    rank: int,
    iters: int,
    reg: float,
    implicit: bool,
) -> tuple:
    ku, ki = jax.random.split(key)
    x = 0.1 * jax.random.normal(ku, (u_n, rank), jnp.float32)
    y = 0.1 * jax.random.normal(ki, (i_n, rank), jnp.float32)
    eye = jnp.eye(rank, dtype=jnp.float32) * reg
    n_blocks = eu.shape[0] // _EDGE_BLOCK

    def accumulate(fixed: jnp.ndarray, src: jnp.ndarray, dst: jnp.ndarray,
                   n_out: int) -> tuple:
        """Per-row grams/rhs from observed edges, one edge block at a time.

        Explicit: A_u = sum_e w y y^T, b_u = sum_e w r y.
        Implicit (Hu-Koren): confidence c = 1 + alpha*r on observed cells
        (alpha arrives premultiplied in ``ew``), identity confidence
        elsewhere -> A_u = Y^T Y + sum_e (c-1) y y^T, b_u = sum_e c y.
        """
        src_b = src.reshape(n_blocks, _EDGE_BLOCK)
        dst_b = dst.reshape(n_blocks, _EDGE_BLOCK)
        r_b = er.reshape(n_blocks, _EDGE_BLOCK)
        w_b = ew.reshape(n_blocks, _EDGE_BLOCK)

        def blk(carry, inp):
            a_acc, b_acc = carry
            s, d, r, w = inp
            yf = fixed[d]                               # (B, F)
            if implicit:
                aw = w * r                              # c - 1 = alpha*r
                # c * pref(=1); padding edges (w == 0) must contribute 0
                bw = jnp.where(w > 0, 1.0 + w * r, 0.0)
            else:
                aw = w
                bw = w * r
            outer = (aw[:, None, None] * yf[:, :, None]) * yf[:, None, :]
            a_acc = a_acc.at[s].add(outer)
            b_acc = b_acc.at[s].add(bw[:, None] * yf)
            return (a_acc, b_acc), None

        a0 = jnp.zeros((n_out, rank, rank), jnp.float32)
        b0 = jnp.zeros((n_out, rank), jnp.float32)
        (a, b), _ = jax.lax.scan(blk, (a0, b0), (src_b, dst_b, r_b, w_b))
        return a, b

    def step(carry, _):
        x, y = carry
        a, b = accumulate(y, eu, ei, u_n)
        if implicit:
            a = a + (y.T @ y)[None]
        x = jnp.linalg.solve(a + eye[None], b[..., None])[..., 0]
        a, b = accumulate(x, ei, eu, i_n)
        if implicit:
            a = a + (x.T @ x)[None]
        y = jnp.linalg.solve(a + eye[None], b[..., None])[..., 0]
        return (x, y), None

    (x, y), _ = jax.lax.scan(step, (x, y), None, length=iters)
    return x, y


def als_train_coo(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    num_users: int,
    num_items: int,
    rank: int = 10,
    iters: int = 10,
    reg: float = 0.1,
    implicit: bool = False,
    alpha: float = 40.0,
    seed: int = 0,
) -> tuple:
    """Sparse ALS on (user, item, rating) triples — never builds (U, I)."""
    e = len(users)
    pad = (-e) % _EDGE_BLOCK
    eu = np.pad(np.asarray(users, np.int32), (0, pad))
    ei = np.pad(np.asarray(items, np.int32), (0, pad))
    er = np.pad(np.asarray(ratings, np.float32), (0, pad))
    ew = np.pad(
        np.full(e, alpha if implicit else 1.0, np.float32), (0, pad)
    )  # padded edges carry weight 0 -> contribute nothing
    x, y = _als_run_coo(
        jnp.asarray(eu), jnp.asarray(ei), jnp.asarray(er), jnp.asarray(ew),
        jax.random.PRNGKey(seed), int(num_users), int(num_items),
        int(rank), int(iters), float(reg), bool(implicit),
    )
    return np.asarray(x), np.asarray(y)


def als_predict(user_factors: np.ndarray, item_factors: np.ndarray,
                users: np.ndarray, items: np.ndarray) -> np.ndarray:
    """Pairwise predicted affinity x_u · y_i for aligned index arrays."""
    return np.einsum(
        "nf,nf->n", user_factors[np.asarray(users)], item_factors[np.asarray(items)]
    )
