"""AccessAnomaly estimator (reference: cyber/anomaly/collaborative_filtering.py).

Per-tenant pipeline: index users/resources, optionally add complement
samples, factorize access likelihoods with device ALS, then standardize
predicted affinities per tenant so transform can emit
``anomaly_score = -(affinity - mean) / std`` — high score = the factor
model did not expect this user to touch this resource.

Unseen users/resources at transform time get score 0 (no evidence),
matching the reference's neutral handling.
"""

from __future__ import annotations

from typing import Any, Optional

import zlib

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import ComplexParam, Param
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.cyber.als import als_predict, als_train_coo
from mmlspark_tpu.cyber.complement import complement_sample


class _AccessAnomalyParams:
    tenant_col = Param("tenant column", default="tenant")
    user_col = Param("user column", default="user")
    res_col = Param("resource column", default="res")
    likelihood_col = Param("access count/likelihood column", default="likelihood")
    output_col = Param("anomaly score output column", default="anomaly_score")
    rank = Param("ALS factor rank", default=10, type_=int)
    max_iter = Param("ALS iterations", default=10, type_=int)
    reg_param = Param("ALS regularization", default=0.1, type_=float)
    implicit = Param("implicit-feedback ALS (confidence weights)", default=False, type_=bool)
    alpha = Param("implicit confidence scale", default=40.0, type_=float)
    complement_factor = Param(
        "complement samples per observed row (explicit mode)", default=2.0, type_=float
    )
    seed = Param("rng seed", default=0, type_=int)


class AccessAnomaly(Estimator, _AccessAnomalyParams):
    def fit(self, df: DataFrame) -> "AccessAnomalyModel":
        tc = self.get("tenant_col")
        tenants = (
            df[tc] if tc in df.columns else np.zeros(df.count(), np.int64)
        )
        users_raw = df[self.get("user_col")]
        res_raw = df[self.get("res_col")]
        lc = self.get("likelihood_col")
        likes = (
            np.asarray(df[lc], np.float64)
            if lc in df.columns
            else np.ones(df.count(), np.float64)
        )

        per_tenant: dict = {}
        for t in np.unique(tenants) if len(tenants) else []:
            sel = np.asarray(tenants == t)
            u_labels = sorted(set(np.asarray(users_raw)[sel].tolist()))
            r_labels = sorted(set(np.asarray(res_raw)[sel].tolist()))
            u_map = {v: i for i, v in enumerate(u_labels)}
            r_map = {v: i for i, v in enumerate(r_labels)}
            u_idx = np.array([u_map[v] for v in np.asarray(users_raw)[sel]], np.int64)
            r_idx = np.array([r_map[v] for v in np.asarray(res_raw)[sel]], np.int64)
            vals = likes[sel]

            # sparse COO edges, duplicates aggregated — the ratings matrix
            # is never densified (Spark ALS consumes the same triples)
            keys = u_idx * len(r_labels) + r_idx
            uniq, inv = np.unique(keys, return_inverse=True)
            agg = np.zeros(len(uniq), np.float32)
            np.add.at(agg, inv, vals.astype(np.float32))
            eu = (uniq // len(r_labels)).astype(np.int64)
            er_ = (uniq % len(r_labels)).astype(np.int64)
            if not self.get("implicit") and self.get("complement_factor") > 0:
                cu, ci = complement_sample(
                    u_idx, r_idx, len(u_labels), len(r_labels),
                    self.get("complement_factor"),
                    # independent complement draws per tenant
                    self.get("seed") + (zlib.crc32(str(t).encode()) % (1 << 20)),
                )
                # observed zeros: rating-0 edges with full weight; drop any
                # that collide with real observations
                ckeys = cu * len(r_labels) + ci
                fresh = ~np.isin(ckeys, uniq)
                eu = np.concatenate([eu, cu[fresh]])
                er_ = np.concatenate([er_, ci[fresh]])
                agg = np.concatenate([agg, np.zeros(fresh.sum(), np.float32)])

            uf, rf = als_train_coo(
                eu, er_, agg,
                num_users=len(u_labels),
                num_items=len(r_labels),
                rank=min(self.get("rank"), max(1, min(len(u_labels), len(r_labels)) - 1)),
                iters=self.get("max_iter"),
                reg=self.get("reg_param"),
                implicit=self.get("implicit"),
                alpha=self.get("alpha"),
                seed=self.get("seed"),
            )
            # standardization stats over the OBSERVED pairs' affinities
            obs_aff = als_predict(uf, rf, u_idx, r_idx)
            mean = float(obs_aff.mean()) if len(obs_aff) else 0.0
            std = float(obs_aff.std()) if len(obs_aff) > 1 else 1.0
            per_tenant[t] = {
                "user_labels": u_labels,
                "res_labels": r_labels,
                "user_factors": uf,
                "res_factors": rf,
                "mean": mean,
                "std": std if std > 0 else 1.0,
            }

        m = AccessAnomalyModel(**{k: v for k, v in self._paramMap.items()})
        m.set(tenant_models=per_tenant)
        return m


class AccessAnomalyModel(Model, _AccessAnomalyParams):
    tenant_models = ComplexParam("{tenant: factors + index maps + stats}")

    def transform(self, df: DataFrame) -> DataFrame:
        models = self.get_or_fail("tenant_models")
        tc = self.get("tenant_col")
        # label->index maps built once per transform, shared by all partitions
        maps = {
            t: (
                {v: i for i, v in enumerate(tm["user_labels"])},
                {v: i for i, v in enumerate(tm["res_labels"])},
            )
            for t, tm in models.items()
        }

        def fn(p: dict) -> dict:
            n = len(next(iter(p.values()))) if p else 0
            users = p[self.get("user_col")]
            res = p[self.get("res_col")]
            tenants = p[tc] if tc in p else np.zeros(n, np.int64)
            scores = np.zeros(n, np.float64)
            for t in set(tenants.tolist()) if n else set():
                tm = models.get(t)
                if tm is None:
                    continue  # unknown tenant: neutral 0
                u_map, r_map = maps[t]
                sel = np.where(np.asarray(tenants == t))[0]
                ui = np.array([u_map.get(users[pos], -1) for pos in sel], np.int64)
                ri = np.array([r_map.get(res[pos], -1) for pos in sel], np.int64)
                ok = (ui >= 0) & (ri >= 0)  # unseen entities stay neutral 0
                if ok.any():
                    aff = als_predict(
                        tm["user_factors"], tm["res_factors"], ui[ok], ri[ok]
                    )
                    scores[sel[ok]] = -(aff - tm["mean"]) / tm["std"]
            q = dict(p)
            q[self.get("output_col")] = scores
            return q

        return df.map_partitions(fn)
