"""Synthetic access dataset (reference: cyber/dataset.py).

Generates per-tenant user→resource access logs with block structure: users
belong to departments that concentrate their accesses on that department's
resources — so cross-department accesses are the plantable anomalies.
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame


def synthetic_access_df(
    n_tenants: int = 1,
    n_departments: int = 3,
    users_per_dept: int = 10,
    resources_per_dept: int = 8,
    accesses_per_user: int = 20,
    cross_dept_prob: float = 0.02,
    seed: int = 0,
) -> DataFrame:
    rng = np.random.RandomState(seed)
    rows_t, rows_u, rows_r, rows_l = [], [], [], []
    for t in range(n_tenants):
        for d in range(n_departments):
            for u in range(users_per_dept):
                user = f"t{t}_d{d}_u{u}"
                for _ in range(accesses_per_user):
                    others = [x for x in range(n_departments) if x != d]
                    if others and rng.rand() < cross_dept_prob:
                        od = rng.choice(others)
                    else:
                        od = d
                    r = rng.randint(0, resources_per_dept)
                    rows_t.append(t)
                    rows_u.append(user)
                    rows_r.append(f"t{t}_d{od}_r{r}")
                    rows_l.append(1.0)
    return DataFrame.from_dict(
        {
            "tenant": np.array(rows_t, np.int64),
            "user": np.array(rows_u, dtype=object),
            "res": np.array(rows_r, dtype=object),
            "likelihood": np.array(rows_l, np.float64),
        }
    )
