"""CyberML — access-anomaly detection (reference: src/main/python/mmlspark/cyber/,
SURVEY.md §2.16, Python-only in the reference).

``AccessAnomaly``: per-tenant collaborative filtering over user→resource
access counts (reference: collaborative_filtering.py:1-988 on Spark ALS);
unusual accesses score high because the factor model assigns them low
predicted affinity. TPU-first: ALS itself is rebuilt as batched
least-squares solves on device (mmlspark_tpu.cyber.als) — each alternating
half-step is one jitted program of stacked (F, F) solves, not a Spark job.
"""

from mmlspark_tpu.cyber.als import als_train, als_predict
from mmlspark_tpu.cyber.anomaly import AccessAnomaly, AccessAnomalyModel
from mmlspark_tpu.cyber.complement import ComplementSampler, complement_sample
from mmlspark_tpu.cyber.dataset import synthetic_access_df
from mmlspark_tpu.cyber.scalers import LinearScalarScaler, StandardScalarScaler

__all__ = [
    "als_train",
    "als_predict",
    "AccessAnomaly",
    "AccessAnomalyModel",
    "ComplementSampler",
    "complement_sample",
    "synthetic_access_df",
    "StandardScalarScaler",
    "LinearScalarScaler",
]
