"""Text featurization (featurize/text/*.scala).

TextFeaturizer = tokenize -> [stopwords] -> ngrams -> hashingTF -> [idf],
mirroring the reference's internal pipeline assembly
(TextFeaturizer.scala); MultiNGram concatenates several n-gram lengths;
PageSplitter chunks long strings by character budget.
"""

from __future__ import annotations

import re
from typing import Any, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, Partition
from mmlspark_tpu.core.params import HasInputCol, HasOutputCol, Param
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.ops.hashing import hashing_tf

_DEFAULT_STOPWORDS = frozenset(
    "a an and are as at be by for from has he in is it its of on that the to was were will with".split()
)


def _tokenize(s: str, pattern: str, to_lower: bool, min_len: int) -> list:
    if to_lower:
        s = s.lower()
    toks = re.split(pattern, s)
    return [t for t in toks if len(t) >= min_len]


def _ngrams(tokens: list, n: int) -> list:
    if n <= 1:
        return list(tokens)
    return [" ".join(tokens[i: i + n]) for i in range(len(tokens) - n + 1)]


class TextFeaturizer(Estimator, HasInputCol, HasOutputCol):
    use_tokenizer = Param("tokenize input", default=True, type_=bool)
    tokenizer_pattern = Param("split regex", default=r"\s+", type_=str)
    to_lower_case = Param("lowercase", default=True, type_=bool)
    min_token_length = Param("min token length", default=0, type_=int)
    use_stop_words_remover = Param("remove stopwords", default=False, type_=bool)
    use_ngram = Param("emit n-grams", default=False, type_=bool)
    n_gram_length = Param("n", default=2, type_=int)
    num_features = Param("hash space", default=1 << 18, type_=int)
    binary = Param("binary term counts", default=False, type_=bool)
    use_idf = Param("apply inverse document frequency", default=True, type_=bool)
    min_doc_freq = Param("idf min document frequency", default=1, type_=int)

    def _docs(self, col: np.ndarray) -> list:
        docs = []
        for s in col:
            toks = (
                _tokenize(
                    str(s),
                    self.get("tokenizer_pattern"),
                    self.get("to_lower_case"),
                    self.get("min_token_length"),
                )
                if self.get("use_tokenizer")
                else list(s)
            )
            if self.get("use_stop_words_remover"):
                toks = [t for t in toks if t not in _DEFAULT_STOPWORDS]
            if self.get("use_ngram"):
                toks = _ngrams(toks, self.get("n_gram_length"))
            docs.append(toks)
        return docs

    def fit(self, df: DataFrame) -> "TextFeaturizerModel":
        model = TextFeaturizerModel(
            input_col=self.get_or_fail("input_col"),
            output_col=self.get_or_fail("output_col"),
        )
        for p in (
            "use_tokenizer tokenizer_pattern to_lower_case min_token_length "
            "use_stop_words_remover use_ngram n_gram_length num_features binary"
        ).split():
            model.set(p, self.get(p))
        if self.get("use_idf"):
            docs = self._docs(df[self.get_or_fail("input_col")])
            tf = hashing_tf(docs, self.get("num_features"), binary=True)
            n_docs = max(len(docs), 1)
            dfreq = tf.sum(axis=0)
            dfreq = np.where(dfreq >= self.get("min_doc_freq"), dfreq, 0.0)
            idf = np.log((n_docs + 1.0) / (dfreq + 1.0)).astype(np.float32)
            model.set(idf_vector=idf.tolist())
        return model


class TextFeaturizerModel(Model, HasInputCol, HasOutputCol):
    use_tokenizer = Param("tokenize input", default=True, type_=bool)
    tokenizer_pattern = Param("split regex", default=r"\s+", type_=str)
    to_lower_case = Param("lowercase", default=True, type_=bool)
    min_token_length = Param("min token length", default=0, type_=int)
    use_stop_words_remover = Param("remove stopwords", default=False, type_=bool)
    use_ngram = Param("emit n-grams", default=False, type_=bool)
    n_gram_length = Param("n", default=2, type_=int)
    num_features = Param("hash space", default=1 << 18, type_=int)
    binary = Param("binary term counts", default=False, type_=bool)
    idf_vector = Param("idf weights (set when use_idf)", type_=list)

    def transform(self, df: DataFrame) -> DataFrame:
        helper = TextFeaturizer()
        for p in (
            "use_tokenizer tokenizer_pattern to_lower_case min_token_length "
            "use_stop_words_remover use_ngram n_gram_length"
        ).split():
            helper.set(p, self.get(p))
        idf = self.get("idf_vector")
        idf_arr = np.asarray(idf, dtype=np.float32) if idf is not None else None

        def fn(p: Partition) -> np.ndarray:
            docs = helper._docs(p[self.get_or_fail("input_col")])
            tf = hashing_tf(docs, self.get("num_features"), binary=self.get("binary"))
            if idf_arr is not None:
                tf = tf * idf_arr
            return tf

        return df.with_column(self.get_or_fail("output_col"), fn)


class MultiNGram(Transformer, HasInputCol, HasOutputCol):
    """Concatenate n-grams of several lengths (featurize/text/MultiNGram.scala).
    Input: token-array column; output: object column of combined n-gram lists."""

    lengths = Param("n-gram lengths", default=[1, 2, 3], type_=list)

    def transform(self, df: DataFrame) -> DataFrame:
        lengths = self.get("lengths")
        ic, oc = self.get_or_fail("input_col"), self.get_or_fail("output_col")

        def fn(p: Partition) -> np.ndarray:
            out = np.empty(len(p[ic]), dtype=object)
            for i, toks in enumerate(p[ic]):
                toks = list(toks)
                combined: list = []
                for n in lengths:
                    combined.extend(_ngrams(toks, int(n)))
                out[i] = combined
            return out

        return df.with_column(oc, fn)


class PageSplitter(Transformer, HasInputCol, HasOutputCol):
    """Split long strings into page chunks (featurize/text/PageSplitter.scala):
    word-boundary preferred, hard split beyond maximum."""

    maximum_page_length = Param("max chars per page", default=5000, type_=int)
    minimum_page_length = Param("min chars before boundary split", default=4500, type_=int)
    boundary_regex = Param("boundary pattern", default=r"\s", type_=str)

    def transform(self, df: DataFrame) -> DataFrame:
        mx = self.get("maximum_page_length")
        mn = self.get("minimum_page_length")
        pat = re.compile(self.get("boundary_regex"))
        ic, oc = self.get_or_fail("input_col"), self.get_or_fail("output_col")

        def split_one(s: str) -> list:
            pages = []
            i = 0
            while i < len(s):
                chunk = s[i: i + mx]
                if i + mx >= len(s):
                    pages.append(chunk)
                    break
                cut = None
                for m in pat.finditer(chunk, mn):
                    cut = m.start()
                cut = cut if cut is not None else mx
                pages.append(chunk[:cut])
                i += cut if cut > 0 else mx
            return pages

        def fn(p: Partition) -> np.ndarray:
            out = np.empty(len(p[ic]), dtype=object)
            for i, s in enumerate(p[ic]):
                out[i] = split_one(str(s))
            return out

        return df.with_column(oc, fn)
