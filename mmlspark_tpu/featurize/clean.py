"""CleanMissingData + DataConversion (featurize/CleanMissingData.scala,
featurize/DataConversion.scala)."""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, Partition
from mmlspark_tpu.core.params import HasInputCols, HasOutputCols, Param
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer


class CleanMissingData(Estimator, HasInputCols, HasOutputCols):
    """Impute NaNs: Mean | Median | Custom."""

    cleaning_mode = Param("Mean|Median|Custom", default="Mean", type_=str)
    custom_value = Param("fill value for Custom mode", type_=float)

    def fit(self, df: DataFrame) -> "CleanMissingDataModel":
        mode = self.get("cleaning_mode")
        in_cols = self.get_or_fail("input_cols")
        fills = []
        for c in in_cols:
            col = df[c].astype(np.float64)
            col = col[~np.isnan(col)]
            if mode == "Mean":
                fills.append(float(col.mean()) if len(col) else 0.0)
            elif mode == "Median":
                fills.append(float(np.median(col)) if len(col) else 0.0)
            elif mode == "Custom":
                fills.append(float(self.get_or_fail("custom_value")))
            else:
                raise ValueError(f"unknown cleaning_mode {mode!r}")
        return CleanMissingDataModel(
            input_cols=in_cols,
            output_cols=self.get("output_cols") or in_cols,
            fill_values=fills,
        )


class CleanMissingDataModel(Model, HasInputCols, HasOutputCols):
    fill_values = Param("per-column fill values", default=[], type_=list)

    def pipeline_io(self) -> tuple:
        """Declared I/O for the pipeline compiler. Host-bound by design:
        the staged transform fills in float64 (fitted means/medians are
        not float32-representable), which an x64-disabled device program
        cannot bit-match."""
        ins = self.get_or_fail("input_cols")
        return tuple(ins), tuple(self.get("output_cols") or ins)

    def transform(self, df: DataFrame) -> DataFrame:
        ins = self.get_or_fail("input_cols")
        outs = self.get("output_cols") or ins
        fills = self.get("fill_values")

        def fn(p: Partition) -> Partition:
            q = dict(p)
            for c, o, f in zip(ins, outs, fills):
                col = np.asarray(p[c], dtype=np.float64)
                q[o] = np.where(np.isnan(col), f, col)
            return q

        return df.map_partitions(fn)


class DataConversion(Transformer):
    """Cast columns between types (featurize/DataConversion.scala)."""

    cols = Param("columns to convert", default=[], type_=list)
    convert_to = Param(
        "boolean|byte|short|integer|long|float|double|string|date", default="double", type_=str
    )
    date_time_format = Param("strftime format for date conversion", type_=str)

    _DTYPES = {
        "boolean": np.bool_,
        "byte": np.int8,
        "short": np.int16,
        "integer": np.int32,
        "long": np.int64,
        "float": np.float32,
        "double": np.float64,
    }

    def transform(self, df: DataFrame) -> DataFrame:
        target = self.get("convert_to")

        def fn(p: Partition) -> Partition:
            q = dict(p)
            for c in self.get("cols"):
                col = p[c]
                if target == "string":
                    q[c] = np.array([str(v) for v in col], dtype=object)
                elif target == "date":
                    import datetime as _dt

                    fmt = self.get("date_time_format") or "%Y-%m-%d %H:%M:%S"
                    q[c] = np.array(
                        [_dt.datetime.strptime(str(v), fmt) for v in col], dtype=object
                    )
                else:
                    q[c] = col.astype(self._DTYPES[target])
            return q

        return df.map_partitions(fn)
