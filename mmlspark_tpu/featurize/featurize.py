"""Featurize / AssembleFeatures — automatic featurization to one dense column.

Reference: featurize/Featurize.scala + AssembleFeatures.scala — numeric
passthrough (+missing imputation), low-cardinality strings one-hot,
high-cardinality strings hashed, vectors concatenated; output is a single
fixed-width features column (FeaturizeUtilities defaults:
numFeaturesDefault=262144, numFeaturesTreeOrNNBased=numFeaturesDefault/5 —
LightGBMUtils.scala:50-63).

The dense fixed-width output is exactly the TPU-friendly layout: every
downstream trainer sees a static (batch, num_features) matrix.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, Partition
from mmlspark_tpu.core.params import HasOutputCol, Param
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.ops.hashing import hash_strings

NUM_FEATURES_DEFAULT = 262144
NUM_FEATURES_TREE_OR_NN = NUM_FEATURES_DEFAULT // 5
# Dense assembly caps the per-column hash block: the reference's 262144-wide
# space assumes sparse vectors; a dense (n, 262144) float32 block would be
# ~1MB/row. The full 2^b sparse space lives in the VW module's segment-sum
# path; here high-cardinality strings get a capped one-hot-hash block.
MAX_DENSE_HASH = 4096


class Featurize(Estimator, HasOutputCol):
    input_cols = Param("columns to featurize (default: all but output)", type_=list)
    output_col = Param("assembled features column", default="features", type_=str)
    number_of_features = Param(
        "hash space size for high-cardinality/text columns",
        default=NUM_FEATURES_TREE_OR_NN,
        type_=int,
    )
    one_hot_encode_categoricals = Param("one-hot low-cardinality strings", default=True, type_=bool)
    max_one_hot = Param("cardinality threshold for one-hot", default=100, type_=int)
    allow_images = Param("API parity; images featurized elsewhere", default=False, type_=bool)

    def fit(self, df: DataFrame) -> "FeaturizeModel":
        if df.count() == 0:
            raise ValueError("Featurize: cannot fit on an empty dataframe")
        cols = self.get("input_cols") or [
            c for c in df.columns if c != self.get("output_col")
        ]
        plans: list = []
        schema = df.schema
        for c in cols:
            info = schema.get(c)
            col = df[c]
            if info is None:
                raise KeyError(f"column {c!r} not in dataframe")
            if info.kind in ("vector", "tensor"):
                dim = int(np.prod(info.shape))
                plans.append({"col": c, "kind": "vector", "dim": dim})
            elif info.dtype != "object":
                x = col.astype(np.float64)
                mean = float(np.nanmean(x)) if len(x) else 0.0
                plans.append({"col": c, "kind": "numeric", "fill": mean})
            else:
                uniq = sorted({str(v) for v in col})
                if self.get("one_hot_encode_categoricals") and len(uniq) <= self.get("max_one_hot"):
                    plans.append({"col": c, "kind": "onehot", "levels": uniq})
                else:
                    plans.append(
                        {
                            "col": c,
                            "kind": "hash",
                            "dim": min(self.get("number_of_features"), MAX_DENSE_HASH),
                        }
                    )
        return FeaturizeModel(output_col=self.get("output_col"), plans=plans)


class FeaturizeModel(Model, HasOutputCol):
    plans = Param("per-column featurization plans", default=[], type_=list)

    def pipeline_io(self) -> tuple:
        """Exact column deps for the pipeline compiler's planner."""
        return (
            tuple(p["col"] for p in self.get("plans")),
            (self.get("output_col"),),
        )

    def fusable_kernel(self) -> Any:
        """Jit-fusable when every plan is numeric or vector: the staged
        path then computes f64-upcast -> NaN-fill -> f32-cast and dense
        reshapes, all of which lower to bit-identical f32 ops on device
        (the guard pins input dtypes for which the double-rounding paths
        agree). One-hot/hash plans walk object columns on host — those
        configurations classify host-bound."""
        from mmlspark_tpu.compiler.kernels import StageKernel

        plans = self.get("plans")
        if not plans or any(p["kind"] not in ("numeric", "vector") for p in plans):
            return None
        oc = self.get("output_col")
        reads = tuple(dict.fromkeys(p["col"] for p in plans))

        def fn(cols: dict) -> dict:
            import jax.numpy as jnp

            n = None
            blocks = []
            for plan in plans:
                x = cols[plan["col"]]
                n = x.shape[0] if n is None else n
                if plan["kind"] == "numeric":
                    x = x.astype(jnp.float32)
                    x = jnp.where(
                        jnp.isnan(x), jnp.float32(plan["fill"]), x
                    )
                    blocks.append(x[:, None])
                else:  # vector
                    blocks.append(x.astype(jnp.float32).reshape(n, -1))
            return {oc: jnp.concatenate(blocks, axis=1)}

        from mmlspark_tpu.compiler.kernels import guard_f32_safe

        return StageKernel(reads=reads, writes=(oc,), fn=fn,
                           guard=guard_f32_safe, cost_hint=0.5)

    @property
    def feature_dim(self) -> int:
        d = 0
        for plan in self.get("plans"):
            if plan["kind"] == "numeric":
                d += 1
            elif plan["kind"] == "onehot":
                d += len(plan["levels"])
            else:
                d += plan["dim"]
        return d

    def transform(self, df: DataFrame) -> DataFrame:
        plans = self.get("plans")
        oc = self.get("output_col")

        def fn(p: Partition) -> Partition:
            n = len(next(iter(p.values()))) if p else 0
            blocks = []
            for plan in plans:
                col = p[plan["col"]]
                kind = plan["kind"]
                if kind == "numeric":
                    x = np.asarray(col, dtype=np.float64)
                    x = np.where(np.isnan(x), plan["fill"], x)
                    blocks.append(x[:, None].astype(np.float32))
                elif kind == "vector":
                    x = np.asarray(col)
                    if x.dtype == object and n:
                        # rows arriving from JSON (from_rows/from_dict) carry
                        # per-row python lists in an object column
                        x = np.stack([
                            np.asarray(v, dtype=np.float32).ravel() for v in col
                        ])
                    x = np.asarray(x, dtype=np.float32)
                    # reshape(-1) cannot infer a width from 0 rows
                    shape = (n, -1) if n else (0, plan["dim"])
                    blocks.append(x.reshape(shape))
                elif kind == "onehot":
                    levels = {v: i for i, v in enumerate(plan["levels"])}
                    out = np.zeros((n, len(levels)), dtype=np.float32)
                    for i, v in enumerate(col):
                        j = levels.get(str(v))
                        if j is not None:
                            out[i, j] = 1.0
                    blocks.append(out)
                elif kind == "hash":
                    out = np.zeros((n, plan["dim"]), dtype=np.float32)
                    idx = hash_strings([str(v) for v in col]) % np.uint32(plan["dim"])
                    out[np.arange(n), idx.astype(np.int64)] = 1.0
                    blocks.append(out)
            q = dict(p)
            q[oc] = (
                np.concatenate(blocks, axis=1) if blocks else np.zeros((n, 0), np.float32)
            )
            return q

        return df.map_partitions(fn)
