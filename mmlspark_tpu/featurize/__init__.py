from mmlspark_tpu.featurize.clean import CleanMissingData, CleanMissingDataModel, DataConversion
from mmlspark_tpu.featurize.featurize import Featurize, FeaturizeModel
from mmlspark_tpu.featurize.indexers import (
    IndexToValue,
    ValueIndexer,
    ValueIndexerModel,
)
from mmlspark_tpu.featurize.text import (
    MultiNGram,
    PageSplitter,
    TextFeaturizer,
    TextFeaturizerModel,
)

__all__ = [
    "CleanMissingData",
    "CleanMissingDataModel",
    "DataConversion",
    "Featurize",
    "FeaturizeModel",
    "ValueIndexer",
    "ValueIndexerModel",
    "IndexToValue",
    "TextFeaturizer",
    "TextFeaturizerModel",
    "MultiNGram",
    "PageSplitter",
]
