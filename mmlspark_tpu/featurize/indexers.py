"""Categorical indexing with column-metadata levels.

ValueIndexer/IndexToValue (featurize/ValueIndexer.scala, IndexToValue.scala)
with the reference's CategoricalMap-in-metadata design
(core/schema/Categoricals.scala): fitted levels ride in the DataFrame's
column metadata so downstream stages (TrainClassifier label round-trip) can
recover original values.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, Partition
from mmlspark_tpu.core.params import HasInputCol, HasOutputCol, Param
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.core.schema import CATEGORICAL_KEY


class ValueIndexer(Estimator, HasInputCol, HasOutputCol):
    """Fit: collect distinct values -> levels; transform: value -> index."""

    def fit(self, df: DataFrame) -> "ValueIndexerModel":
        col = df[self.get_or_fail("input_col")]
        key = col.astype(str) if col.dtype == object else col
        uniq = np.unique(key)
        levels = [v.item() if hasattr(v, "item") else v for v in uniq]
        return ValueIndexerModel(
            input_col=self.get("input_col"),
            output_col=self.get_or_fail("output_col"),
            levels=list(map(_plain, levels)),
        )


def _plain(v: Any) -> Any:
    return v.item() if hasattr(v, "item") else v


class ValueIndexerModel(Model, HasInputCol, HasOutputCol):
    levels = Param("ordered distinct values", default=[], type_=list)

    def transform(self, df: DataFrame) -> DataFrame:
        levels = self.get("levels")
        table = {str(v): i for i, v in enumerate(levels)}
        ic, oc = self.get_or_fail("input_col"), self.get_or_fail("output_col")

        def fn(p: Partition) -> np.ndarray:
            return np.array([table.get(str(v), -1) for v in p[ic]], dtype=np.int32)

        out = df.with_column(oc, fn)
        return out.with_column_metadata(oc, {CATEGORICAL_KEY: levels})


class IndexToValue(Transformer, HasInputCol, HasOutputCol):
    """Inverse mapping using metadata levels (featurize/IndexToValue.scala)."""

    def transform(self, df: DataFrame) -> DataFrame:
        ic, oc = self.get_or_fail("input_col"), self.get_or_fail("output_col")
        levels = df.column_metadata(ic).get(CATEGORICAL_KEY)
        if levels is None:
            raise ValueError(f"column {ic!r} carries no categorical levels metadata")
        lv = np.array(levels, dtype=object)

        def fn(p: Partition) -> np.ndarray:
            idx = np.asarray(p[ic], dtype=np.int64)
            out = np.empty(len(idx), dtype=object)
            valid = (idx >= 0) & (idx < len(lv))
            out[valid] = lv[idx[valid]]
            return out

        return df.with_column(oc, fn)
