"""SLO-driven autoscaling policy for the fleet supervisor.

The supervisor (serving/supervisor.py) keeps N worker processes alive;
``supervise --autoscale`` lets it also decide what N should BE, from the
signals the earlier layers already export:

- **admission sheds** (PR 5): workers answering 429 mean the AIMD limit
  is full — spawn a replica *before* the breaker trips, while the fleet
  is still shedding rather than failing;
- **in-flight utilization**: summed ``inflight/limit`` across workers
  approaching 1.0 is the same overload, seen earlier;
- **SLO burn** (PR 4): a red burn-rate status is the page-now signal —
  scale out even if sheds haven't started;
- **sustained idle**: no accepted traffic, nothing in flight and no
  sheds for ``idle_after_s`` — reap one replica (never below
  ``min_replicas``).

Hysteresis, so the fleet never flaps: scale-out is rate-limited by
``scale_out_cooldown_s``, scale-in by ``scale_in_cooldown_s`` AND the
idle clock (which resets on any activity and on every scale event — a
fresh replica gets a full idle window before it can be judged useless).
One step per decision, clamped to ``[min_replicas, max_replicas]``.

:class:`FleetSignals` turns live ``/metrics`` scrapes (gateway +
rostered workers) into one :class:`ScaleSignals` sample per tick, with
counter deltas computed against the previous scrape. Tests inject
scripted signals instead — the policy is pure.

Fault point ``autoscaler.scale`` fires as the supervisor is about to
act on a decision: an injected error suppresses that scale event
(retried next tick — "the scheduler refused"), ``delay_s`` stalls it.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional

from mmlspark_tpu import obs

_M_REPLICAS = obs.gauge(
    "mmlspark_autoscaler_replicas_count",
    "Worker replicas the autoscaling supervisor currently maintains",
)
_M_EVENTS = obs.counter(
    "mmlspark_autoscaler_scale_events_total",
    "Autoscaler actions taken", labels=("direction",),
)
_M_DESIRED = obs.gauge(
    "mmlspark_autoscaler_desired_replicas_count",
    "Replica count the last policy decision asked for",
)


@dataclass
class ScaleSignals:
    """One tick's worth of fleet-health evidence."""

    shed_delta: float = 0.0        # admission/backpressure 429s since last tick
    inflight: float = 0.0          # summed in-flight requests across workers
    limit: float = 0.0             # summed AIMD limits across workers
    accepted_delta: float = 0.0    # requests accepted since last tick
    slo_status: Optional[int] = None  # obs.slo GREEN/YELLOW/RED (None=unknown)
    breakers_open: int = 0         # open breakers at the gateway

    @property
    def utilization(self) -> float:
        return (self.inflight / self.limit) if self.limit > 0 else 0.0

    @property
    def busy(self) -> bool:
        return (
            self.accepted_delta > 0 or self.inflight > 0
            or self.shed_delta > 0
        )


class Autoscaler:
    """The pure scaling policy: ``decide(current, signals) -> (desired,
    reason)``. Stateful only for hysteresis clocks."""

    def __init__(
        self,
        min_replicas: int = 1,
        max_replicas: int = 4,
        util_threshold: float = 0.85,
        scale_out_cooldown_s: float = 10.0,
        scale_in_cooldown_s: float = 30.0,
        idle_after_s: float = 30.0,
        time_fn: Callable[[], float] = time.monotonic,
    ):
        if min_replicas < 0 or max_replicas < max(1, min_replicas):
            raise ValueError(
                f"need 0 <= min_replicas <= max_replicas, got "
                f"{min_replicas}/{max_replicas}"
            )
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.util_threshold = util_threshold
        self.scale_out_cooldown_s = scale_out_cooldown_s
        self.scale_in_cooldown_s = scale_in_cooldown_s
        self.idle_after_s = idle_after_s
        self._now = time_fn
        now = self._now()
        self._last_out = now - scale_out_cooldown_s  # first overload may act
        self._last_in = now
        self._idle_since = now
        self.events: list = []  # (direction, reason) history

    def _overloaded(self, s: ScaleSignals) -> Optional[str]:
        from mmlspark_tpu.obs import slo

        if s.shed_delta > 0:
            return f"admission shed x{s.shed_delta:.0f}"
        if s.limit > 0 and s.utilization >= self.util_threshold:
            return f"utilization {s.utilization:.2f}"
        if s.slo_status is not None and s.slo_status >= slo.RED:
            return "slo red"
        return None

    def decide(self, current: int, s: ScaleSignals) -> tuple:
        """Returns ``(desired_replicas, reason)``; ``reason`` is ''
        when desired == current. At most one step per call."""
        now = self._now()
        if s.busy:
            self._idle_since = now
        if current < self.min_replicas:
            return self.min_replicas, "below min_replicas"
        if current > self.max_replicas:
            return self.max_replicas, "above max_replicas"
        why = self._overloaded(s)
        if (
            why is not None
            and current < self.max_replicas
            and now - self._last_out >= self.scale_out_cooldown_s
        ):
            self._last_out = now
            self._idle_since = now  # a fresh replica gets a full idle window
            self.events.append(("out", why))
            _M_DESIRED.set(current + 1)
            return current + 1, why
        if (
            why is None
            and not s.busy
            and current > self.min_replicas
            and now - self._idle_since >= self.idle_after_s
            and now - self._last_in >= self.scale_in_cooldown_s
        ):
            self._last_in = now
            self._idle_since = now  # one reap per idle window
            self.events.append(("in", "sustained idle"))
            _M_DESIRED.set(current - 1)
            return current - 1, "sustained idle"
        _M_DESIRED.set(current)
        return current, ""

    @staticmethod
    def note_applied(direction: str) -> None:
        """The supervisor actually acted on a decision (post fault-point)."""
        _M_EVENTS.labels(direction=direction).inc()

    @staticmethod
    def export_replicas(n: int) -> None:
        _M_REPLICAS.set(n)


class FleetSignals:
    """Live signal source: scrape the gateway's and the rostered
    workers' ``/metrics`` into one :class:`ScaleSignals` per call, with
    counter deltas against the previous call. Every scrape failure
    degrades to zeros — a blind autoscaler must hold, not flap."""

    def __init__(
        self,
        registry_url: Optional[str] = None,
        gateway_url: Optional[str] = None,
        service_name: str = "serving",
    ):
        self.registry_url = registry_url
        self.gateway_url = gateway_url
        self.service_name = service_name
        self._prev_shed = None
        self._prev_accepted = None

    def __call__(self) -> ScaleSignals:
        from mmlspark_tpu.obs import slo as slo_mod
        from mmlspark_tpu.serving.fleet import (
            scrape_metrics,
            worker_urls_from_registry,
        )

        shed = accepted = inflight = limit = 0.0
        slo_status = None
        breakers_open = 0
        worker_urls: list = []
        if self.registry_url:
            try:
                worker_urls = worker_urls_from_registry(
                    self.registry_url, self.service_name
                )
            except Exception:  # noqa: BLE001 — registry down: gateway-only view
                pass
        for u in worker_urls:
            parsed = scrape_metrics(u)
            if parsed is None:
                continue
            m = {"server": self.service_name}
            shed += obs.sum_samples(parsed, "mmlspark_admission_shed_total", m)
            accepted += obs.sum_samples(
                parsed, "mmlspark_serving_requests_total", m
            )
            inflight += obs.sum_samples(
                parsed, "mmlspark_admission_inflight_requests", m
            )
            limit += obs.sum_samples(
                parsed, "mmlspark_admission_limit_requests", m
            )
            status = slo_mod.status_from_scrape(parsed)
            if status is not None:
                slo_status = max(slo_status or 0, status)
        if self.gateway_url:
            parsed = scrape_metrics(self.gateway_url)
            if parsed is not None:
                # the gateway's view of worker sheds (429 relays) covers
                # workers the roster scrape missed
                shed += obs.sum_samples(
                    parsed, "mmlspark_gateway_backend_backpressure_total"
                )
                accepted += obs.sum_samples(
                    parsed, "mmlspark_serving_requests_total",
                    {"server": f"{self.service_name}-gateway"},
                )
                status = slo_mod.status_from_scrape(parsed)
                if status is not None:
                    slo_status = max(slo_status or 0, status)
                for (name, _labels), v in parsed.items():
                    if name == "mmlspark_gateway_breaker_state" and v == 1.0:
                        breakers_open += 1
        shed_delta = 0.0 if self._prev_shed is None else max(
            0.0, shed - self._prev_shed
        )
        accepted_delta = 0.0 if self._prev_accepted is None else max(
            0.0, accepted - self._prev_accepted
        )
        self._prev_shed = shed
        self._prev_accepted = accepted
        return ScaleSignals(
            shed_delta=shed_delta,
            inflight=inflight,
            limit=limit,
            accepted_delta=accepted_delta,
            slo_status=slo_status,
            breakers_open=breakers_open,
        )


__all__ = ["Autoscaler", "FleetSignals", "ScaleSignals"]
