"""Continuous learning: streaming feedback -> online training -> zero-drop
publication.

The subsystem that closes the train/serve loop (ROADMAP item 5, the
one-system argument of the TensorFlow paper, arXiv 1605.08695): a
:class:`FeedbackStream` source feeds labeled micro-batches into an
:class:`OnlineTrainer` that incrementally updates the device-resident VW
learner (``vw/learner.py`` stateful SGD — weights AND AdaGrad state stay
on device between micro-batches), and a :class:`Publisher` snapshots the
weights into a versioned ``vw:`` ModelStore spec and drives the existing
load -> warm -> swap path, so a fresh version becomes servable with zero
dropped requests. :class:`OnlineLearningLoop` is the control loop tying
the three together, exporting the **freshness SLO** — the time from an
example entering the system to its model being servable — as burn rates
through ``obs/slo.py``. :class:`Autoscaler` is the SLO-driven scaling
policy the fleet supervisor consults in ``supervise --autoscale`` mode.

See docs/online-learning.md for the architecture walkthrough, freshness
semantics, the autoscaler policy, and the fault-point/metric tables.
"""

from mmlspark_tpu.online.autoscaler import Autoscaler, FleetSignals, ScaleSignals
from mmlspark_tpu.online.feedback import FeedbackStream
from mmlspark_tpu.online.loop import OnlineLearningLoop
from mmlspark_tpu.online.publisher import PublishError, Publisher
from mmlspark_tpu.online.trainer import OnlineTrainer

__all__ = [
    "Autoscaler",
    "FeedbackStream",
    "FleetSignals",
    "OnlineLearningLoop",
    "OnlineTrainer",
    "PublishError",
    "Publisher",
    "ScaleSignals",
]
