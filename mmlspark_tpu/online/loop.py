"""OnlineLearningLoop: the control loop of the continuous-learning
subsystem.

One background thread: poll the :class:`FeedbackStream` for micro-
batches, fold each into the :class:`OnlineTrainer` (device-resident
state), and every ``publish_every_s`` (when new examples arrived) drive
the :class:`Publisher` through the zero-drop load -> warm -> swap path.

Freshness accounting: the loop tracks the OLDEST ingest timestamp among
examples trained since the last successful publication (the watermark).
A publication's freshness is ``servable_time - watermark`` — the worst
example's wait. A FAILED publication keeps the watermark (those
examples are still unserved), so freshness honestly degrades while
publication is broken and the SLO burn pages — the loop retries at the
next due time rather than crashing.

The loop optionally runs its own
:class:`~mmlspark_tpu.obs.slo.SLOEngine` over the process registry with
the :func:`~mmlspark_tpu.obs.slo.freshness_target`, so any process
hosting a loop exports ``mmlspark_slo_*`` burn gauges for the freshness
objective (``fleet online`` wires this up; the deploy smoke's freshness
gate reads them).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Callable, Optional

from mmlspark_tpu import obs
from mmlspark_tpu.online.publisher import PublishError

_M_LOOP_TICKS = obs.counter(
    "mmlspark_online_loop_ticks_total", "Control-loop iterations",
)
_M_PENDING = obs.gauge(
    "mmlspark_online_pending_examples_count",
    "Examples trained but not yet covered by a successful publication",
)
_M_POISONED = obs.counter(
    "mmlspark_online_poisoned_examples_total",
    "Examples in poison chunks discarded after repeated train-step "
    "failures — accounted, never silently lost (chaos/invariants.py)",
)


class OnlineLearningLoop:
    def __init__(
        self,
        stream: Any,
        trainer: Any,
        publisher: Any,
        publish_every_s: float = 2.0,
        min_publish_examples: int = 1,
        poll_s: float = 0.25,
        freshness_budget_ms: Optional[float] = None,
        slo_interval_s: float = 15.0,
        time_fn: Callable[[], float] = time.monotonic,
    ):
        """``freshness_budget_ms``: when set, the loop starts an SLO
        engine evaluating the freshness target against this budget (None
        = the caller owns SLO evaluation)."""
        self.stream = stream
        self.trainer = trainer
        self.publisher = publisher
        self.publish_every_s = float(publish_every_s)
        self.min_publish_examples = max(1, int(min_publish_examples))
        self.poll_s = poll_s
        self.freshness_budget_ms = freshness_budget_ms
        self.slo_interval_s = slo_interval_s
        self._now = time_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.slo_engine: Any = None
        # freshness watermark state
        self._pending_oldest_ts: Optional[float] = None
        self._pending_examples = 0
        self._last_publish_t = 0.0
        self.publish_results: list = []  # successful publish() returns
        # poison-chunk escape: a chunk whose step fails this many times
        # CONSECUTIVELY is discarded (acked away) instead of retried
        # forever — one bad chunk must not head-of-line-block the loop
        self.max_step_retries = 3
        self._step_failures = 0
        self.poisoned_chunks = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "OnlineLearningLoop":
        if self.freshness_budget_ms is not None:
            from mmlspark_tpu.obs import slo

            self.slo_engine = slo.SLOEngine(
                [slo.freshness_target(budget_ms=self.freshness_budget_ms)],
                interval_s=self.slo_interval_s,
            ).start()
        self._last_publish_t = self._now()
        self._thread = threading.Thread(
            target=self._run, name="online-loop", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, final_publish: bool = False) -> None:
        """Stop the loop; ``final_publish=True`` flushes any pending
        examples into one last publication before returning."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(10.0)
        if final_publish and self._pending_examples >= 1:
            try:
                self._publish()
            except PublishError:
                pass
        if self.slo_engine is not None:
            self.slo_engine.stop()

    # -- the loop ------------------------------------------------------------

    def _publish(self) -> None:
        res = self.publisher.publish(
            self.trainer, oldest_ts=self._pending_oldest_ts
        )
        self.publish_results.append(res)
        self._pending_oldest_ts = None
        self._pending_examples = 0
        _M_PENDING.set(0)

    def _tick(self) -> None:
        item = self.stream.poll(self.poll_s)
        if item is not None:
            ts, chunk = item
            try:
                trained = self.trainer.step(chunk)
                self._step_failures = 0
            except BaseException:
                self._step_failures += 1
                if self._step_failures >= self.max_step_retries:
                    # poison chunk: discard (ack so the spill truncates)
                    # rather than hot-retry it forever while everything
                    # behind it goes stale
                    self._step_failures = 0
                    self.poisoned_chunks += 1
                    _M_POISONED.inc(len(chunk))
                    ack = getattr(self.stream, "ack_trained", None)
                    if ack is not None:
                        ack()
                    print(
                        f"online: dropping poison chunk after "
                        f"{self.max_step_retries} failed train steps",
                        file=sys.stderr, flush=True,
                    )
                else:
                    # a transiently-failed step did NOT consume the
                    # chunk: requeue it (retried next tick) so a later
                    # success's ack cannot silently truncate it
                    nack = getattr(self.stream, "nack_failed", None)
                    if nack is not None:
                        nack()
                raise
            # the step succeeded: confirm the spill (disk-backed streams
            # truncate their chunk log; a crash BEFORE this point replays
            # the chunk on restart — no feedback loss)
            ack = getattr(self.stream, "ack_trained", None)
            if ack is not None:
                ack()
            if trained:
                if self._pending_oldest_ts is None or ts < self._pending_oldest_ts:
                    self._pending_oldest_ts = ts
                self._pending_examples += trained
                _M_PENDING.set(self._pending_examples)
        now = self._now()
        if (
            self._pending_examples >= self.min_publish_examples
            and now - self._last_publish_t >= self.publish_every_s
        ):
            self._last_publish_t = now  # back off a full interval on failure
            try:
                self._publish()
            except PublishError as e:
                # the watermark survives: those examples are still not
                # servable, so the NEXT successful publish's freshness
                # includes the outage — the burn rate tells the truth
                print(f"online: publish failed: {e}", file=sys.stderr,
                      flush=True)
        _M_LOOP_TICKS.inc()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — the loop must outlive a tick
                print(f"online: tick failed: {e}", file=sys.stderr, flush=True)
                self._stop.wait(self.poll_s)

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        return {
            "examples": self.trainer.examples,
            "batches": self.trainer.batches,
            "publishes": self.publisher.publishes,
            "publish_failures": self.publisher.failures,
            "pending_examples": self._pending_examples,
            "last_freshness_s": self.publisher.last_freshness_s,
            "freshness_history_s": list(self.publisher.freshness_history),
            "buffered_chunks": self.stream.depth(),
            "dropped_chunks": self.stream.dropped,
        }


__all__ = ["OnlineLearningLoop"]
