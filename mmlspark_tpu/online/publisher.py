"""Publisher: snapshot online-learner weights into a versioned ``vw:``
artifact and drive the ModelStore load -> warm -> swap path.

Zero-drop by construction: publication rides the SAME machinery the
chaos suite already gates — a new version loads and warms in the
background while the old one keeps serving, the alias flip is atomic,
and in-flight batches drain on the old weights (serving/modelstore).
The publisher never touches the dispatch path; a failed publish leaves
the serving alias exactly where it was (the rollback property pinned in
tests/test_online.py).

Targets:

- **in-process store** (``store=``) — the loop runs inside a serving
  worker (tests, bench, single-process deployments);
- **remote workers** (``worker_urls=`` and/or ``registry_url=``) — each
  publish re-resolves the roster and drives every worker's model
  control plane (``POST /models/<m>/load`` with ``activate=never``,
  then ``POST /models/<m>/swap``), so a worker the supervisor just
  restarted picks the fresh version up on the next publish.

Fault point ``online.publish`` fires before the snapshot is written: an
injected error aborts the whole publication (nothing written, nothing
loaded, alias untouched — retried at the next due time), ``delay_s``
stalls only the control path while serving continues.

Freshness: ``publish(trainer, oldest_ts)`` returns — and observes into
``mmlspark_online_freshness_seconds`` — the time from the OLDEST example
folded in since the last successful publish to the moment the new
version was servable everywhere it was pushed.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Optional

import numpy as np

from mmlspark_tpu import obs
from mmlspark_tpu.core import faults

# freshness is seconds-scale (publish cadence + load/warm/swap), not the
# request-latency scale of DEFAULT_BUCKETS — widen to 50 ms .. 2 min
FRESHNESS_BUCKETS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

_M_ATTEMPTS = obs.counter(
    "mmlspark_online_publish_attempts_total",
    "Publication attempts (the freshness SLO's total-events metric)",
)
_M_PUBLISHES = obs.counter(
    "mmlspark_online_publishes_total",
    "Successful online-model publications (servable version flips)",
)
_M_FAILURES = obs.counter(
    "mmlspark_online_publish_failures_total",
    "Publications that failed (fault, store error, no worker flipped)",
)
_M_PUBLISH_S = obs.histogram(
    "mmlspark_online_publish_seconds",
    "Wall time of one publication (snapshot + load + warm + swap)",
)
_M_FRESHNESS = obs.histogram(
    "mmlspark_online_freshness_seconds",
    "Oldest-example-ingested to new-version-servable, per publication",
    buckets=FRESHNESS_BUCKETS,
)
_M_VERSION = obs.gauge(
    "mmlspark_online_published_version_count",
    "Monotonic publication sequence number of the serving online model",
)


class PublishError(Exception):
    """A publication failed end-to-end (the serving alias is unchanged)."""


class Publisher:
    def __init__(
        self,
        model: str = "vw-online",
        snapshot_dir: Optional[str] = None,
        store: Any = None,
        worker_urls: Optional[list] = None,
        registry_url: Optional[str] = None,
        service_name: str = "serving",
        keep_snapshots: int = 4,
        request_timeout_s: float = 60.0,
        time_fn: Callable[[], float] = time.monotonic,
        artifact_store: Any = None,
        artifact_url: Optional[str] = None,
        epoch: Optional[int] = None,
        replicas: int = 0,
    ):
        """``artifact_store`` (an :class:`~mmlspark_tpu.serving.artifacts.
        ArtifactStore`) switches publication to **artifact mode**: each
        snapshot is ``put()`` into the store and workers receive an
        ``artifact:vw:<name>@<sha256>`` spec instead of a filesystem path
        — they pull the bytes over HTTP (hash-verified, resumable) from
        ``artifact_url`` (this process's ingress serving ``/artifacts``)
        or any registry-advertised peer, so the fleet needs NO shared
        filesystem. Leaving it None keeps the shared-fs ``vw:<path>``
        fast path exactly as before.

        ``epoch``: the coordination epoch (committed training
        generation) stamped onto every worker load/swap as a fencing
        token — a worker that has already seen a higher epoch rejects
        the publication with 409 (a SIGSTOP'd zombie coordinator waking
        after a reshard cannot roll the serving fleet back). Bump it
        with :meth:`set_epoch` when the gang reshards; None publishes
        unstamped (pre-fencing behaviour).

        ``replicas``: replication-before-ack (artifact mode only) — the
        snapshot blob must be confirmed installed on this many OTHER
        artifact holders (registry-advertised artifact planes and/or the
        explicit ``worker_urls``) BEFORE any target is driven to load
        it; below quorum the publication raises and the serving alias is
        untouched. The confirmed holders ride the published spec as peer
        hints, so a worker can pull the snapshot even after this
        process's host is gone — the no-shared-fs durability contract.
        0 (default) keeps the single-copy behaviour."""
        if store is None and not worker_urls and not registry_url:
            raise ValueError(
                "Publisher needs a target: store=, worker_urls= or "
                "registry_url="
            )
        self.model = model
        self.snapshot_dir = snapshot_dir or os.path.join(
            os.getcwd(), ".online_snapshots"
        )
        self.store = store
        self.worker_urls = list(worker_urls or ())
        self.registry_url = registry_url
        self.service_name = service_name
        self.keep_snapshots = max(1, int(keep_snapshots))
        self.request_timeout_s = request_timeout_s
        self._now = time_fn
        self.artifact_store = artifact_store
        self.artifact_url = artifact_url
        self.replicas = max(0, int(replicas))
        self.epoch = int(epoch) if epoch is not None else None
        # version ledger for _gc: (snapshot path, artifact digest | None)
        # in publication order — GC never touches a version it cannot
        # first unadvertise (pinned / mid-pull artifacts stay)
        self._published: list = []
        if artifact_store is not None:
            # adopt a previous incarnation's snapshot blobs (the store's
            # index survives restarts): without this, a restarted
            # publisher would re-advertise and retain them forever —
            # the ledger is what keep-last pruning acts on
            import re as _re

            pat = _re.compile(_re.escape(self.model) + r"-v\d{6}\.npz$")
            for ref in artifact_store.refs():
                n, _, d = ref.rpartition("@")
                if pat.match(n):
                    self._published.append(
                        (os.path.join(self.snapshot_dir, n), d)
                    )
        self.seq = 0
        self.publishes = 0
        self.failures = 0
        self.last_freshness_s: Optional[float] = None
        self.freshness_history: list = []  # seconds, per successful publish

    # -- snapshot artifact ---------------------------------------------------

    def _write_snapshot(self, trainer: Any) -> str:
        os.makedirs(self.snapshot_dir, exist_ok=True)
        path = os.path.join(
            self.snapshot_dir, f"{self.model}-v{self.seq:06d}.npz"
        )
        tmp = path + ".tmp"
        meta = trainer.snapshot_meta()
        # atomic: a concurrently-restarting worker re-loading its --load
        # spec must never see a torn file
        with open(tmp, "wb") as f:
            np.savez(
                f,
                weights=trainer.weights_host(),
                meta=json.dumps(meta).encode(),
            )
        os.replace(tmp, path)
        return path

    def _gc(self) -> None:
        """Keep-last pruning with replication safety: a version beyond
        ``keep_snapshots`` is deleted only once it is DRAINED and
        UNADVERTISED — in artifact mode that means the store agreed to
        ``remove()`` its blob (refused while pinned or mid-pull, so a
        worker half-way through a ranged fetch, or an operator pin, keeps
        both the blob and the snapshot file alive). Refused versions are
        retried at the next publication; pruning is hygiene, never
        correctness."""
        try:
            retained: list = []
            for path, digest in self._published[: -self.keep_snapshots]:
                if (
                    digest is not None
                    and self.artifact_store is not None
                    and not self.artifact_store.remove(digest)
                ):
                    # still pinned or mid-pull: stays advertised AND on
                    # disk — never yank bytes a puller is reading;
                    # retried at the next publication
                    retained.append((path, digest))
                    continue
                try:
                    os.remove(path)
                except OSError:
                    pass
            self._published = retained + self._published[-self.keep_snapshots:]
            # legacy sweep (shared-fs mode, pre-restart leftovers): prune
            # by filename, but never a file the ledger says must stay
            keep_names = {os.path.basename(p) for p, _ in self._published}
            snaps = sorted(
                f for f in os.listdir(self.snapshot_dir)
                if f.startswith(f"{self.model}-v") and f.endswith(".npz")
            )
            for f in snaps[: -self.keep_snapshots]:
                if f not in keep_names:
                    os.remove(os.path.join(self.snapshot_dir, f))
        except OSError:
            pass  # pruning is hygiene, not correctness

    # kept as an alias: pre-artifact callers and docs name the old verb
    _prune_snapshots = _gc

    def set_epoch(self, epoch: int) -> None:
        """Advance the publication fencing token (never backwards — a
        publisher cannot un-see an epoch)."""
        e = int(epoch)
        if self.epoch is None or e > self.epoch:
            self.epoch = e

    # -- targets -------------------------------------------------------------

    def _replica_holders(self) -> list:
        """Candidate push targets for replication-before-ack: every
        registry-rostered artifact plane that is not this process, plus
        the explicit worker URLs (their ingress serves ``/artifacts``
        too)."""
        own = (
            [self.artifact_url.rstrip("/")] if self.artifact_url else []
        )
        holders: list = []
        if self.registry_url:
            from mmlspark_tpu.serving.artifacts import registry_holders

            try:
                holders = registry_holders(self.registry_url, exclude=own)
            except Exception:  # noqa: BLE001 — worker_urls still replicate
                holders = []
        for u in self.worker_urls:
            u = u.rstrip("/")
            if u not in holders and u not in own:
                holders.append(u)
        return holders

    def _publish_store(self, spec: str) -> int:
        v = self.store.load(self.model, spec, wait=True, activate="never")
        self.store.swap(self.model, v)
        return 1

    def _resolve_workers(self) -> list:
        urls = list(self.worker_urls)
        if self.registry_url:
            from mmlspark_tpu.serving.fleet import worker_urls_from_registry

            try:
                for u in worker_urls_from_registry(
                    self.registry_url, self.service_name
                ):
                    if u not in urls:
                        urls.append(u)
            except Exception:  # noqa: BLE001 — explicit urls still publish
                pass
        return urls

    def _publish_workers(self, spec: str) -> int:
        from mmlspark_tpu.io.clients import send_request
        from mmlspark_tpu.io.http_schema import HTTPRequestData

        load_body: dict = {"spec": spec, "activate": "never"}
        swap_body: dict = {}
        if self.epoch is not None:
            # the fencing token: workers reject (409) any publication
            # stamped older than the highest epoch they have seen
            load_body["epoch"] = self.epoch
            swap_body["epoch"] = self.epoch
        flipped = 0
        for base in self._resolve_workers():
            base = base.rstrip("/")
            try:
                loaded = send_request(HTTPRequestData(
                    f"{base}/models/{self.model}/load", "POST",
                    {"Content-Type": "application/json"},
                    json.dumps(load_body),
                ), timeout=self.request_timeout_s)
                if loaded["status_code"] not in (200, 202):
                    continue
                swapped = send_request(HTTPRequestData(
                    f"{base}/models/{self.model}/swap", "POST",
                    {"Content-Type": "application/json"},
                    json.dumps(swap_body),
                ), timeout=self.request_timeout_s)
                if swapped["status_code"] == 200:
                    flipped += 1
            except Exception:  # noqa: BLE001 — a dead worker skips, not aborts
                continue
        return flipped

    # -- the publication -----------------------------------------------------

    def publish(self, trainer: Any, oldest_ts: Optional[float] = None) -> dict:
        """Snapshot + load + warm + swap. Returns ``{"version", "path",
        "targets", "freshness_s"}``; raises :class:`PublishError` (after
        counting the failure) when no target flipped — the serving alias
        is unchanged and the caller retries with the same watermark."""
        t0 = self._now()
        _M_ATTEMPTS.inc()
        replicated: list = []
        try:
            # fault point online.publish: an injected error aborts the
            # publication before anything is written or loaded
            faults.inject("online.publish", context={"model": self.model})
            self.seq += 1
            path = self._write_snapshot(trainer)
            digest = None
            if self.artifact_store is not None:
                # artifact mode (no shared fs): workers pull the snapshot
                # over HTTP by digest — from this process's own ingress
                # (the spec-embedded hint) or any registry-advertised
                # peer — hash-verified and resumable
                ref = self.artifact_store.put(
                    path, name=os.path.basename(path)
                )
                digest = ref.digest
                hints = (
                    [self.artifact_url.rstrip("/")]
                    if self.artifact_url else []
                )
                if self.replicas > 0:
                    # replication-before-ack: the snapshot must be
                    # durable on `replicas` OTHER holders before any
                    # worker is told to load it — below quorum this
                    # raises (wrapped into PublishError) and the alias
                    # stays put. Confirmed holders become spec hints so
                    # pullers survive this host dying.
                    confirmed = self.artifact_store.replicate(
                        digest, self._replica_holders(),
                        need=self.replicas,
                        timeout_s=self.request_timeout_s,
                    )
                    replicated = list(confirmed)
                    hints += [
                        u.rstrip("/") for u in confirmed
                        if u.rstrip("/") not in hints
                    ]
                spec = f"artifact:vw:{ref.spec}"
                if hints:
                    spec += "@" + ",".join(hints)
            else:
                spec = f"vw:{path}"
            self._published.append((path, digest))
            targets = 0
            if self.store is not None:
                targets += self._publish_store(spec)
            if self.worker_urls or self.registry_url:
                targets += self._publish_workers(spec)
            if targets == 0:
                raise PublishError(
                    f"no target made {self.model} v{self.seq} servable"
                )
        except Exception as e:
            self.failures += 1
            _M_FAILURES.inc()
            if isinstance(e, PublishError):
                raise
            raise PublishError(f"{type(e).__name__}: {e}") from e
        ready = self._now()
        _M_PUBLISH_S.observe(ready - t0)
        freshness = None
        if oldest_ts is not None:
            freshness = max(0.0, ready - oldest_ts)
            self.last_freshness_s = freshness
            self.freshness_history.append(freshness)
            _M_FRESHNESS.observe(freshness)
        self.publishes += 1
        _M_PUBLISHES.inc()
        _M_VERSION.set(self.seq)
        self._gc()
        return {
            "version": self.seq,
            "path": path,
            "targets": targets,
            "freshness_s": freshness,
            "replicas": replicated,
        }

    def publish_spec(self, spec: str) -> dict:
        """Publish an already-materialized model ``spec`` (any loader
        grammar the targets understand — ``artifact:gbdt:…``, ``zoo:…``)
        through the same epoch-fenced load → warm → swap path as
        :meth:`publish`. No snapshot is written and nothing is GC'd:
        the caller owns the bytes (an experiment controller's artifact
        store, a shared-fs file). Raises :class:`PublishError` when no
        target flipped — the serving alias is unchanged."""
        t0 = self._now()
        _M_ATTEMPTS.inc()
        try:
            faults.inject(
                "online.publish", context={"model": self.model, "spec": spec}
            )
            self.seq += 1
            targets = 0
            if self.store is not None:
                targets += self._publish_store(spec)
            if self.worker_urls or self.registry_url:
                targets += self._publish_workers(spec)
            if targets == 0:
                raise PublishError(
                    f"no target made {self.model} v{self.seq} servable"
                )
        except Exception as e:
            self.failures += 1
            _M_FAILURES.inc()
            if isinstance(e, PublishError):
                raise
            raise PublishError(f"{type(e).__name__}: {e}") from e
        _M_PUBLISH_S.observe(self._now() - t0)
        self.publishes += 1
        _M_PUBLISHES.inc()
        _M_VERSION.set(self.seq)
        return {"version": self.seq, "spec": spec, "targets": targets}


__all__ = ["FRESHNESS_BUCKETS", "PublishError", "Publisher"]
