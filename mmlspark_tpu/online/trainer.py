"""OnlineTrainer: incremental VW training over feedback micro-batches.

A thin stateful wrapper over ``vw/learner.py``'s
:func:`train_sparse_sgd_state`: the full optimizer state (weights,
AdaGrad accumulator, schedule counter) lives in ``self.state`` and stays
**device-resident between micro-batches** — each ``step()`` is one jit
dispatch warm-started from the previous state, and the weights only
come to host when the publisher snapshots them.

Because the whole state is carried (not just weights), feeding rows
chunk-by-chunk is *bit-identical* to one batch ``train_sparse_sgd`` call
over the concatenated rows whenever chunk sizes are multiples of the
minibatch size on the unsharded path (the warm-start identity pinned in
tests/test_online.py). ``distributed=True`` opts into the mesh
``pmean`` allreduce per pass on sharded meshes (VW's allreduce-per-pass
semantics), trading that identity for multi-chip throughput.

Input micro-batches are plain DataFrames with a label column plus
either a sparse features column (``{"i": ..., "v": ...}`` rows — the
``VowpalWabbitFeaturizer`` output, or raw JSON dicts from the HTTP
ingest path) or a text column hashed here through the featurizer.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional

import numpy as np

from mmlspark_tpu import obs
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.vw.learner import (
    LOSS_HINGE,
    LOSS_LOGISTIC,
    LOSSES,
    SGDState,
    sgd_init,
    train_sparse_sgd_state,
)
from mmlspark_tpu.vw.sparse import pad_sparse_batch

_M_EXAMPLES = obs.counter(
    "mmlspark_online_examples_total", "Examples trained by the online loop",
)
_M_BATCHES = obs.counter(
    "mmlspark_online_batches_total", "Micro-batches trained",
)
_M_TRAIN_S = obs.histogram(
    "mmlspark_online_train_seconds", "Wall time per training micro-batch",
)


class OnlineTrainer:
    """Incremental trainer: ``step(chunk)`` folds one micro-batch into
    the resident learner state.

    ``text_col``: hash this string column through a
    ``VowpalWabbitFeaturizer`` (whitespace-split tokens) instead of
    reading pre-hashed ``features_col`` rows. ``no_constant`` mirrors
    the estimator's intercept semantics — published weights score
    identically through the ``vw:`` serving handler and the
    ``VowpalWabbit*Model`` stages."""

    def __init__(
        self,
        num_bits: int = 18,
        loss: str = LOSS_LOGISTIC,
        lr: float = 0.5,
        power_t: float = 0.5,
        l2: float = 0.0,
        adaptive: bool = True,
        batch: int = 64,
        num_passes: int = 1,
        features_col: str = "features",
        label_col: str = "label",
        weight_col: Optional[str] = None,
        text_col: Optional[str] = None,
        no_constant: bool = False,
        distributed: bool = False,
        quantile_tau: float = 0.5,
        seed: int = 0,
        initial_weights: Optional[np.ndarray] = None,
    ):
        if loss not in LOSSES:
            raise ValueError(f"loss must be one of {LOSSES}, got {loss!r}")
        self.num_bits = int(num_bits)
        self.loss = loss
        self.lr = lr
        self.power_t = power_t
        self.l2 = l2
        self.adaptive = adaptive
        self.batch = int(batch)
        self.num_passes = int(num_passes)
        self.features_col = features_col
        self.label_col = label_col
        self.weight_col = weight_col
        self.text_col = text_col
        self.no_constant = no_constant
        self.distributed = distributed
        self.quantile_tau = quantile_tau
        self.seed = seed
        self.state: SGDState = sgd_init(self.num_bits, initial_weights)
        self.examples = 0
        self.batches = 0
        self._featurizer: Any = None

    # -- featurization -------------------------------------------------------

    def _featurize(self, chunk: DataFrame) -> tuple:
        """Chunk -> (idx, val, y, wt) padded arrays, constant appended."""
        from mmlspark_tpu.vw.estimators import _append_constant

        if self.text_col is not None and self.text_col in chunk.columns:
            if self._featurizer is None:
                from mmlspark_tpu.vw.featurizer import VowpalWabbitFeaturizer

                self._featurizer = VowpalWabbitFeaturizer(
                    input_cols=[self.text_col],
                    string_split_input_cols=[self.text_col],
                    output_col=self.features_col,
                    num_bits=self.num_bits,
                    seed=self.seed,
                )
            chunk = self._featurizer.transform(chunk)
        if self.features_col in chunk.columns:
            rows = chunk[self.features_col]
            norm = np.empty(len(rows), dtype=object)
            for r, cell in enumerate(rows):
                # rows may be JSON dicts with list values; pad_sparse_batch
                # indexes/assigns them like arrays already, but a missing
                # key must fail loudly per row, not per chunk
                norm[r] = {"i": cell["i"], "v": cell["v"]}
        elif "i" in chunk.columns and "v" in chunk.columns:
            # the HTTP ingest wire shape: flat rows {"i": [...],
            # "v": [...], "label": y} become per-row sparse cells
            iv, vv = chunk["i"], chunk["v"]
            norm = np.empty(len(chunk), dtype=object)
            for r in range(len(chunk)):
                norm[r] = {"i": iv[r], "v": vv[r]}
        else:
            raise ValueError(
                f"micro-batch has no {self.features_col!r} column and no "
                f"i/v pair (columns: {chunk.columns})"
            )
        idx, val = pad_sparse_batch(norm)
        if not self.no_constant:
            idx, val = _append_constant(idx, val, self.num_bits)
        y = np.asarray(chunk[self.label_col], np.float64).astype(np.float32)
        if self.loss in (LOSS_LOGISTIC, LOSS_HINGE):
            y = np.where(y > 0, 1.0, -1.0).astype(np.float32)
        wt = None
        if self.weight_col and self.weight_col in chunk.columns:
            wt = np.asarray(chunk[self.weight_col], np.float64).astype(
                np.float32
            )
        return idx, val, y, wt

    # -- training ------------------------------------------------------------

    def step(self, chunk: DataFrame) -> int:
        """Fold one micro-batch into the learner state; returns rows
        trained (0 for an empty chunk)."""
        n = len(chunk)
        if n == 0:
            return 0
        idx, val, y, wt = self._featurize(chunk)
        return self.step_arrays(idx, val, y, wt)

    def step_arrays(
        self,
        idx: np.ndarray,
        val: np.ndarray,
        y: np.ndarray,
        wt: Optional[np.ndarray] = None,
    ) -> int:
        t0 = time.perf_counter()
        self.state = train_sparse_sgd_state(
            idx, val, y, wt, self.num_bits, self.state,
            loss=self.loss, num_passes=self.num_passes, batch=self.batch,
            lr=self.lr, power_t=self.power_t, l2=self.l2,
            adaptive=self.adaptive, distributed=self.distributed,
            quantile_tau=self.quantile_tau,
        )
        n = int(len(y))
        self.examples += n
        self.batches += 1
        _M_EXAMPLES.inc(n)
        _M_BATCHES.inc()
        _M_TRAIN_S.observe(time.perf_counter() - t0)
        return n

    # -- snapshots -----------------------------------------------------------

    def weights_host(self) -> np.ndarray:
        """Pull the current weights to host (the publish-time sync)."""
        return np.asarray(self.state.w, np.float32)

    def snapshot_meta(self) -> dict:
        """What a published artifact must carry to score identically."""
        return {
            "num_bits": self.num_bits,
            "loss": self.loss,
            "no_constant": self.no_constant,
            "quantile_tau": self.quantile_tau,
            "examples": self.examples,
        }

    def to_model(self) -> Any:
        """The current weights as a fitted ``VowpalWabbit*Model`` stage
        (classification for logistic/hinge, regression otherwise) — the
        offline-scoring view of the online learner."""
        from mmlspark_tpu.core.dataframe import DataFrame as DF
        from mmlspark_tpu.vw.estimators import (
            VowpalWabbitClassificationModel,
            VowpalWabbitRegressionModel,
        )

        cls = (
            VowpalWabbitClassificationModel
            if self.loss in (LOSS_LOGISTIC, LOSS_HINGE)
            else VowpalWabbitRegressionModel
        )
        m = cls()
        m.set(
            weights=self.weights_host(),
            num_bits=self.num_bits,
            features_col=self.features_col,
            no_constant=self.no_constant,
            loss_function=self.loss,
            performance_statistics=DF.from_dict(
                {"rows": [self.examples], "batches": [self.batches]}
            ),
        )
        return m


__all__ = ["OnlineTrainer"]
