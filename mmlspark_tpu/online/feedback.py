"""FeedbackStream: the labeled-example source of the continuous-training
loop.

Two intake modes behind one ``poll()`` surface:

- **push** — ``push(chunk)`` from any producer thread; the HTTP ingest
  endpoint (``serve()``: ``POST /ingest`` on a WorkerServer, so ``GET
  /metrics`` comes for free) is a push producer. The buffer is bounded:
  past ``max_chunks`` the OLDEST chunk is dropped and counted — under
  sustained overload a freshness-driven trainer wants the newest
  feedback, not a queue of stale examples.
- **pull** — ``from_generator`` / ``from_streaming_dataframe`` /
  ``from_csv`` wrap a re-iterable chunk source; ``poll()`` draws the
  next chunk on demand. This is the test/backfill shape, and keeps the
  source :class:`~mmlspark_tpu.io.stream.StreamingDataFrame`-compatible
  (``materialize(max_rows=...)`` on an unbounded feedback source stops
  at the cap — the io/stream contract the online tests pin).

Every chunk carries its **ingest timestamp** (``time_fn`` at push/pull),
the left edge of the freshness SLO: example ingested -> model servable.

Fault point ``online.ingest`` fires per accepted chunk: an injected
error refuses the chunk (the HTTP endpoint answers 503 and buffers
nothing — chaos for the producer's retry handling), ``delay_s`` stalls
intake.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Iterator, Optional

from mmlspark_tpu import obs
from mmlspark_tpu.core import faults
from mmlspark_tpu.core.dataframe import DataFrame

_M_INGESTED = obs.counter(
    "mmlspark_online_ingested_total",
    "Feedback examples accepted into the stream buffer",
)
_M_CHUNKS = obs.counter(
    "mmlspark_online_ingest_chunks_total",
    "Feedback micro-batches accepted into the stream buffer",
)
_M_DROPPED = obs.counter(
    "mmlspark_online_dropped_chunks_total",
    "Oldest chunks dropped by the bounded buffer under overload",
)
_M_DEPTH = obs.gauge(
    "mmlspark_online_buffer_depth_count",
    "Feedback micro-batches buffered awaiting training",
)
_M_BUF_EXAMPLES = obs.gauge(
    "mmlspark_online_buffered_examples_count",
    "Feedback examples buffered awaiting training — a term of the "
    "conservation law ingested == trained + buffered + shed + poisoned "
    "(chaos/invariants.py)",
)
_M_SHED_EXAMPLES = obs.counter(
    "mmlspark_online_shed_examples_total",
    "Feedback examples in chunks deliberately shed by the bounded "
    "buffer (freshest-wins) — accounted, never silently lost",
)
_M_REFUSED = obs.counter(
    "mmlspark_online_ingest_refused_total",
    "Ingest requests refused (injected fault or malformed rows)",
)
_M_SPILL_REPLAYED = obs.counter(
    "mmlspark_online_spill_replayed_total",
    "Feedback examples replayed from the disk spill after a restart",
)
_M_SPILL_PENDING = obs.gauge(
    "mmlspark_online_spill_pending_count",
    "Spilled micro-batches not yet confirmed trained",
)

_JSON = {"Content-Type": "application/json"}


def _np_default(o: Any) -> Any:
    import numpy as np

    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, np.generic):
        return o.item()
    raise TypeError(f"not JSON-serializable: {type(o)!r}")


def _df_rows(df: DataFrame) -> list:
    cols = df.columns
    return [{c: df[c][i] for c in cols} for i in range(len(df))]


class _SpillLog:
    """Append-only chunk log backing a FeedbackStream.

    Layout: ``spill-<n>.jsonl`` segment files of JSON records
    ``{"seq", "ts", "rows"}`` plus an ``ACKED`` watermark file (the
    largest seq confirmed trained; atomic rewrite). Chunks leave the
    buffer oldest-first (trained or shed), so acknowledgement is a
    watermark, not a set; segments wholly below the watermark are
    unlinked — that is the "truncated on successful train step"
    guarantee. On restart, records above the watermark replay."""

    def __init__(self, path: str, segment_chunks: int = 64):
        self.path = path
        self.segment_chunks = max(1, int(segment_chunks))
        os.makedirs(path, exist_ok=True)
        self._f = None
        self._seg_idx = -1
        self._seg_count = 0
        # per-segment max seq, maintained in memory (append/replay) so
        # ack() can unlink without re-reading files under the lock
        self._seg_max: dict = {}
        segs = self._segments()
        if segs:
            self._seg_idx = max(int(s.split("-")[1].split(".")[0])
                                for s in segs)

    def _segments(self) -> list:
        return sorted(
            e for e in os.listdir(self.path)
            if e.startswith("spill-") and e.endswith(".jsonl")
        )

    def watermark(self) -> int:
        try:
            with open(os.path.join(self.path, "ACKED")) as f:
                return int(f.read().strip() or -1)
        except (OSError, ValueError):
            return -1

    def append(self, seq: int, ts: float, df: DataFrame) -> None:
        if self._f is None or self._seg_count >= self.segment_chunks:
            if self._f is not None:
                self._f.close()
            self._seg_idx += 1
            self._f = open(
                os.path.join(self.path, f"spill-{self._seg_idx:06d}.jsonl"),
                "a",
            )
            self._seg_count = 0
        self._f.write(json.dumps(
            {"seq": seq, "ts": ts, "rows": _df_rows(df)},
            default=_np_default,
        ) + "\n")
        self._f.flush()
        self._seg_count += 1
        name = f"spill-{self._seg_idx:06d}.jsonl"
        self._seg_max[name] = max(self._seg_max.get(name, -1), seq)

    def ack(self, watermark: int) -> None:
        """Persist the trained watermark and unlink fully-acked
        segments (the current write segment is never unlinked). Unlink
        eligibility comes from the in-memory per-segment max seq — no
        file re-reads under the stream lock; a segment whose max is
        unknown (shouldn't happen) just survives until restart."""
        tmp = os.path.join(self.path, f".ACKED-{os.getpid()}")
        with open(tmp, "w") as f:
            f.write(str(watermark))
        os.replace(tmp, os.path.join(self.path, "ACKED"))
        current = (
            f"spill-{self._seg_idx:06d}.jsonl" if self._seg_idx >= 0 else ""
        )
        for seg, max_seq in list(self._seg_max.items()):
            if seg == current or max_seq > watermark:
                continue
            try:
                os.unlink(os.path.join(self.path, seg))
            except OSError:
                pass
            del self._seg_max[seg]

    def replay(self) -> list:
        """Unacked ``(seq, ts, rows)`` records in seq order."""
        wm = self.watermark()
        out = []
        for seg in self._segments():
            try:
                with open(os.path.join(self.path, seg)) as f:
                    for line in f:
                        if not line.strip():
                            continue
                        rec = json.loads(line)
                        self._seg_max[seg] = max(
                            self._seg_max.get(seg, -1), rec["seq"]
                        )
                        if rec["seq"] > wm:
                            out.append(
                                (rec["seq"], rec["ts"], rec["rows"])
                            )
            except (OSError, ValueError, KeyError):
                continue  # torn tail of a crashed writer: best-effort
        out.sort(key=lambda r: r[0])
        return out

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class FeedbackStream:
    """Bounded, timestamped micro-batch buffer with optional pull source.

    ``max_chunks`` bounds memory; overflow drops the OLDEST buffered
    chunk (counted in ``mmlspark_online_dropped_chunks_total``).
    ``time_fn`` stamps ingest times (monotonic by default — freshness is
    an interval, not a wall-clock date)."""

    def __init__(
        self,
        source: Optional[Callable[[], Iterator[DataFrame]]] = None,
        max_chunks: int = 1024,
        time_fn: Callable[[], float] = time.monotonic,
        spill_dir: Optional[str] = None,
        spill_segment_chunks: int = 64,
    ):
        """``spill_dir``: optional durability — every PUSHED micro-batch
        is appended to an on-disk chunk log (:class:`_SpillLog`) before
        it is buffered, replayed into the buffer on construction after a
        crash, and truncated once the consumer confirms training
        (:meth:`ack_trained`, wired by OnlineLearningLoop). Pull-source
        chunks never spill — their source is already durable/re-iterable.
        Bounded-buffer sheds are acknowledged as handled (deliberate
        freshest-wins policy, counted), so only genuinely-untrained
        pushes ever replay."""
        self._buf: deque = deque()  # (ingest_ts, DataFrame, seq-or-None)
        self._buf_examples = 0      # running sum of len() over _buf
        self._cond = threading.Condition()
        self._max_chunks = max(1, int(max_chunks))
        self._now = time_fn
        self._source = source
        self._iter: Optional[Iterator[DataFrame]] = None
        self._exhausted = False
        self._closed = False
        self.ingested = 0   # examples accepted
        self.dropped = 0    # chunks dropped by the bound
        self.dropped_examples = 0
        self.replayed = 0   # examples restored from the spill
        self._ingress: Any = None
        self._router: Optional[threading.Thread] = None
        # spill bookkeeping: chunks leave the buffer oldest-first, so the
        # trained/shed frontier is a seq watermark
        self._spill: Optional[_SpillLog] = None
        self._seq = 0
        # chunks polled out, awaiting ack: (seq, ts, chunk) — the chunk
        # is kept so a FAILED train step can requeue it (nack_failed)
        self._handed: list = []
        self._done: set = set()     # seqs trained or deliberately shed
        self._watermark = -1
        self._spill_lock = threading.Lock()
        if spill_dir:
            self._spill = _SpillLog(spill_dir, spill_segment_chunks)
            self._watermark = self._spill.watermark()
            self._seq = self._watermark + 1
            now = self._now()
            for seq, ts, rows in self._spill.replay():
                chunk = DataFrame.from_rows(rows)
                # monotonic stamps do not survive a reboot (the clock
                # restarts): clamp to "now", so a replayed chunk's age
                # counts from replay — conservative, never garbage
                self._buf.append((min(ts, now), chunk, seq))
                self._buf_examples += len(chunk)
                self._seq = max(self._seq, seq + 1)
                self.replayed += len(chunk)
                _M_SPILL_REPLAYED.inc(len(chunk))
            # the bound applies to replayed backlog too: re-shed the
            # oldest past max_chunks (freshest-wins holds across a
            # crash; the sheds are acked so they never replay again)
            while len(self._buf) > self._max_chunks:
                self._shed_oldest_locked()
            _M_SPILL_PENDING.set(self._spill_pending_locked())
            self._export_buf_locked()

    # -- construction --------------------------------------------------------

    @staticmethod
    def from_generator(
        make_chunk: Callable[[int], Optional[DataFrame]],
        num_chunks: Optional[int] = None,
        **kw: Any,
    ) -> "FeedbackStream":
        """``make_chunk(i)`` -> DataFrame or None (None = end of stream);
        ``num_chunks=None`` = unbounded (the live-feedback shape)."""

        def source() -> Iterator[DataFrame]:
            i = 0
            while num_chunks is None or i < num_chunks:
                chunk = make_chunk(i)
                if chunk is None:
                    return
                yield chunk
                i += 1

        return FeedbackStream(source=source, **kw)

    @staticmethod
    def from_streaming_dataframe(sdf: Any, **kw: Any) -> "FeedbackStream":
        """Wrap a :class:`StreamingDataFrame` (file/CSV-backed feedback
        logs replay through the same loop as live traffic)."""
        return FeedbackStream(source=sdf.iter_chunks, **kw)

    @staticmethod
    def from_csv(path: str, chunk_rows: int = 4096, **kw: Any) -> "FeedbackStream":
        from mmlspark_tpu.io.stream import StreamingDataFrame

        return FeedbackStream.from_streaming_dataframe(
            StreamingDataFrame.from_csv(path, chunk_rows=chunk_rows), **kw
        )

    # -- push intake ---------------------------------------------------------

    def push(self, chunk: DataFrame, ts: Optional[float] = None) -> int:
        """Buffer one micro-batch; returns rows accepted. Raises when the
        ``online.ingest`` fault point injects an error (the chunk is NOT
        buffered) or the stream is closed."""
        if self._closed:
            raise RuntimeError("feedback stream is closed")
        # fault point online.ingest: an injected error refuses this chunk
        # (producer-visible), delay_s stalls intake
        faults.inject("online.ingest", context={"rows": len(chunk)})
        ts = self._now() if ts is None else ts
        seq = None
        if self._spill is not None:
            # spill BEFORE buffering: once push() returns, a crash
            # cannot lose this chunk (replayed on restart). The disk
            # write holds only the spill lock — a slow disk must not
            # stall concurrent poll()/ingest on the buffer condition
            with self._spill_lock:
                seq = self._seq
                self._seq += 1
                self._spill.append(seq, ts, chunk)
        with self._cond:
            if seq is not None:
                _M_SPILL_PENDING.set(self._spill_pending_locked())
            self._buf.append((ts, chunk, seq))
            self._buf_examples += len(chunk)
            if len(self._buf) > self._max_chunks:
                self._shed_oldest_locked()  # freshest-wins
            self.ingested += len(chunk)
            self._export_buf_locked()
            self._cond.notify()
        _M_INGESTED.inc(len(chunk))
        _M_CHUNKS.inc()
        return len(chunk)

    # -- spill acknowledgement -------------------------------------------------

    def _shed_oldest_locked(self) -> None:
        """Drop the oldest buffered chunk, keeping every term of the
        conservation law (ingested == trained+buffered+shed+poisoned,
        chaos/invariants.py) in one place for BOTH shed sites: live
        overflow in push() and replayed-backlog overflow on restart. A
        deliberate shed is HANDLED, not lost: acking it keeps the spill
        from resurrecting rejected backlog."""
        _, shed, shed_seq = self._buf.popleft()
        self._buf_examples -= len(shed)
        self.dropped += 1
        self.dropped_examples += len(shed)
        _M_DROPPED.inc()
        _M_SHED_EXAMPLES.inc(len(shed))
        if shed_seq is not None:
            self._mark_done_locked(shed_seq)

    def _spill_pending_locked(self) -> int:
        return max(
            0, (self._seq - 1 - self._watermark) - len(self._done)
        )

    def _mark_done_locked(self, seq: int) -> None:
        self._done.add(seq)
        advanced = False
        while (self._watermark + 1) in self._done:
            self._watermark += 1
            self._done.discard(self._watermark)
            advanced = True
        if advanced and self._spill is not None:
            self._spill.ack(self._watermark)
        if self._spill is not None:
            _M_SPILL_PENDING.set(self._spill_pending_locked())

    def ack_trained(self) -> None:
        """Confirm every chunk currently handed out by :meth:`poll` was
        folded into the model — the spill truncates up to the trained
        watermark. Called by OnlineLearningLoop after each successful
        train step; a crash between poll and ack replays the chunk. A
        FAILED step must :meth:`nack_failed` first, or its chunk would
        ride a later success's acknowledgement."""
        with self._cond:
            handed, self._handed = self._handed, []
            for seq, _, _ in handed:
                if seq is not None:
                    self._mark_done_locked(seq)

    def nack_failed(self) -> None:
        """Requeue every handed-out-but-unconfirmed chunk at the FRONT
        of the buffer (original order): a train step that raised did not
        consume its chunk — it is retried by the next poll, and the
        spill keeps it replayable meanwhile."""
        with self._cond:
            handed, self._handed = self._handed, []
            for seq, ts, chunk in reversed(handed):
                self._buf.appendleft((ts, chunk, seq))
                self._buf_examples += len(chunk)
            self._export_buf_locked()

    def spill_pending(self) -> int:
        """Spilled chunks not yet confirmed trained (0 without a spill)."""
        if self._spill is None:
            return 0
        with self._cond:
            return self._spill_pending_locked()

    def _export_buf_locked(self) -> None:
        """Export buffer depth in chunks AND examples (the latter is a
        term of the invariant checker's conservation law). The example
        count is an incrementally-maintained integer — recomputing the
        sum under the condition lock would cost O(max_chunks) on every
        ingest/pop and serialize producers against the consumer."""
        _M_DEPTH.set(len(self._buf))
        _M_BUF_EXAMPLES.set(self._buf_examples)

    # -- consumption ---------------------------------------------------------

    def depth(self) -> int:
        with self._cond:
            return len(self._buf)

    def poll(self, timeout_s: float = 0.25) -> Optional[tuple]:
        """Next ``(ingest_ts, DataFrame)`` micro-batch, or None.

        Buffered (pushed) chunks win; otherwise a pull source is drawn
        from (stamped at draw time — that IS its ingest into the
        system); otherwise block up to ``timeout_s`` for a push."""
        with self._cond:
            if self._buf:
                ts0, chunk0, seq0 = self._buf.popleft()
                self._buf_examples -= len(chunk0)
                # seq may be None (no spill): still tracked, so
                # nack_failed() can requeue a transiently-failed chunk
                # on ANY stream, not only disk-backed ones
                self._handed.append((seq0, ts0, chunk0))
                self._export_buf_locked()
                return (ts0, chunk0)
        if self._source is not None and not self._exhausted:
            if self._iter is None:
                self._iter = self._source()
            # fault point BEFORE the draw: an injected refusal leaves the
            # chunk in the iterator (retried next poll), matching the
            # push path where the producer keeps the refused chunk —
            # firing after next() would silently lose examples
            faults.inject("online.ingest", context={"mode": "pull"})
            try:
                chunk = next(self._iter)
            except StopIteration:
                self._exhausted = True
                return None
            self.ingested += len(chunk)
            _M_INGESTED.inc(len(chunk))
            _M_CHUNKS.inc()
            return (self._now(), chunk)
        with self._cond:
            if not self._buf and timeout_s > 0:
                self._cond.wait(timeout_s)
            if self._buf:
                ts0, chunk0, seq0 = self._buf.popleft()
                self._buf_examples -= len(chunk0)
                self._handed.append((seq0, ts0, chunk0))
                self._export_buf_locked()
                return (ts0, chunk0)
        return None

    @property
    def exhausted(self) -> bool:
        """A pull source ran dry (push streams never exhaust)."""
        return self._exhausted and self.depth() == 0

    # -- HTTP ingest endpoint ------------------------------------------------

    def serve(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        name: str = "serving-online",
    ) -> Any:
        """Start the HTTP ingest ingress: ``POST /ingest`` with
        ``{"rows": [{...}, ...]}`` (or one bare row object) buffers a
        micro-batch; ``GET /health`` answers liveness; ``GET /metrics``
        is served inline by the WorkerServer machinery. Returns the
        :class:`ServiceInfo` (registered under ``name`` by the fleet
        wiring so ``fleet top`` and the deploy smoke can find the loop).
        """
        from mmlspark_tpu.serving.server import WorkerServer

        srv = WorkerServer(host=host, port=port, name=name)
        info = srv.start()
        self._ingress = srv
        self._router = threading.Thread(
            target=self._ingest_loop, name="online-ingest", daemon=True
        )
        self._router.start()
        return info

    def _ingest_loop(self) -> None:
        srv = self._ingress
        while not self._closed:
            reqs = srv.get_next_batch(max_n=64, timeout_s=0.25)
            for r in reqs:
                try:
                    self._ingest_one(r)
                except Exception as e:  # noqa: BLE001 — ingress must survive
                    _M_REFUSED.inc()
                    srv.reply_to(
                        r.id,
                        json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}
                        ).encode(),
                        503, _JSON,
                    )
            if reqs:
                srv.auto_commit()
        for r in srv.get_next_batch(max_n=1_000_000, timeout_s=0.0):
            srv.reply_to(r.id, b"ingest stopping", 503)

    def _ingest_one(self, r: Any) -> None:
        path = r.path.split("?", 1)[0]
        if path in ("/health", "/healthz") and r.method == "GET":
            self._ingress.reply_to(
                r.id,
                json.dumps(
                    {"status": "ok", "buffered_chunks": self.depth()}
                ).encode(),
                200, _JSON,
            )
            return
        if path != "/ingest" or r.method != "POST":
            self._ingress.reply_to(
                r.id, b'{"error": "POST /ingest"}', 404, _JSON
            )
            return
        body = json.loads(r.body) if r.body else {}
        rows = body["rows"] if isinstance(body, dict) and "rows" in body \
            else [body]
        if (
            not isinstance(rows, list) or not rows
            or not all(isinstance(x, dict) for x in rows)
        ):
            raise ValueError("rows must be a non-empty list of objects")
        n = self.push(DataFrame.from_rows(rows))
        self._ingress.reply_to(
            r.id,
            json.dumps(
                {"accepted": n, "buffered_chunks": self.depth()}
            ).encode(),
            200, _JSON,
        )

    def close(self) -> None:
        self._closed = True
        if self._router is not None:
            self._router.join(5.0)
        if self._ingress is not None:
            self._ingress.stop()
        if self._spill is not None:
            self._spill.close()
        with self._cond:
            self._cond.notify_all()


__all__ = ["FeedbackStream"]
