"""FeedbackStream: the labeled-example source of the continuous-training
loop.

Two intake modes behind one ``poll()`` surface:

- **push** — ``push(chunk)`` from any producer thread; the HTTP ingest
  endpoint (``serve()``: ``POST /ingest`` on a WorkerServer, so ``GET
  /metrics`` comes for free) is a push producer. The buffer is bounded:
  past ``max_chunks`` the OLDEST chunk is dropped and counted — under
  sustained overload a freshness-driven trainer wants the newest
  feedback, not a queue of stale examples.
- **pull** — ``from_generator`` / ``from_streaming_dataframe`` /
  ``from_csv`` wrap a re-iterable chunk source; ``poll()`` draws the
  next chunk on demand. This is the test/backfill shape, and keeps the
  source :class:`~mmlspark_tpu.io.stream.StreamingDataFrame`-compatible
  (``materialize(max_rows=...)`` on an unbounded feedback source stops
  at the cap — the io/stream contract the online tests pin).

Every chunk carries its **ingest timestamp** (``time_fn`` at push/pull),
the left edge of the freshness SLO: example ingested -> model servable.

Fault point ``online.ingest`` fires per accepted chunk: an injected
error refuses the chunk (the HTTP endpoint answers 503 and buffers
nothing — chaos for the producer's retry handling), ``delay_s`` stalls
intake.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Callable, Iterator, Optional

from mmlspark_tpu import obs
from mmlspark_tpu.core import faults
from mmlspark_tpu.core.dataframe import DataFrame

_M_INGESTED = obs.counter(
    "mmlspark_online_ingested_total",
    "Feedback examples accepted into the stream buffer",
)
_M_CHUNKS = obs.counter(
    "mmlspark_online_ingest_chunks_total",
    "Feedback micro-batches accepted into the stream buffer",
)
_M_DROPPED = obs.counter(
    "mmlspark_online_dropped_chunks_total",
    "Oldest chunks dropped by the bounded buffer under overload",
)
_M_DEPTH = obs.gauge(
    "mmlspark_online_buffer_depth_count",
    "Feedback micro-batches buffered awaiting training",
)
_M_REFUSED = obs.counter(
    "mmlspark_online_ingest_refused_total",
    "Ingest requests refused (injected fault or malformed rows)",
)

_JSON = {"Content-Type": "application/json"}


class FeedbackStream:
    """Bounded, timestamped micro-batch buffer with optional pull source.

    ``max_chunks`` bounds memory; overflow drops the OLDEST buffered
    chunk (counted in ``mmlspark_online_dropped_chunks_total``).
    ``time_fn`` stamps ingest times (monotonic by default — freshness is
    an interval, not a wall-clock date)."""

    def __init__(
        self,
        source: Optional[Callable[[], Iterator[DataFrame]]] = None,
        max_chunks: int = 1024,
        time_fn: Callable[[], float] = time.monotonic,
    ):
        self._buf: deque = deque()  # (ingest_ts, DataFrame)
        self._cond = threading.Condition()
        self._max_chunks = max(1, int(max_chunks))
        self._now = time_fn
        self._source = source
        self._iter: Optional[Iterator[DataFrame]] = None
        self._exhausted = False
        self._closed = False
        self.ingested = 0   # examples accepted
        self.dropped = 0    # chunks dropped by the bound
        self._ingress: Any = None
        self._router: Optional[threading.Thread] = None

    # -- construction --------------------------------------------------------

    @staticmethod
    def from_generator(
        make_chunk: Callable[[int], Optional[DataFrame]],
        num_chunks: Optional[int] = None,
        **kw: Any,
    ) -> "FeedbackStream":
        """``make_chunk(i)`` -> DataFrame or None (None = end of stream);
        ``num_chunks=None`` = unbounded (the live-feedback shape)."""

        def source() -> Iterator[DataFrame]:
            i = 0
            while num_chunks is None or i < num_chunks:
                chunk = make_chunk(i)
                if chunk is None:
                    return
                yield chunk
                i += 1

        return FeedbackStream(source=source, **kw)

    @staticmethod
    def from_streaming_dataframe(sdf: Any, **kw: Any) -> "FeedbackStream":
        """Wrap a :class:`StreamingDataFrame` (file/CSV-backed feedback
        logs replay through the same loop as live traffic)."""
        return FeedbackStream(source=sdf.iter_chunks, **kw)

    @staticmethod
    def from_csv(path: str, chunk_rows: int = 4096, **kw: Any) -> "FeedbackStream":
        from mmlspark_tpu.io.stream import StreamingDataFrame

        return FeedbackStream.from_streaming_dataframe(
            StreamingDataFrame.from_csv(path, chunk_rows=chunk_rows), **kw
        )

    # -- push intake ---------------------------------------------------------

    def push(self, chunk: DataFrame, ts: Optional[float] = None) -> int:
        """Buffer one micro-batch; returns rows accepted. Raises when the
        ``online.ingest`` fault point injects an error (the chunk is NOT
        buffered) or the stream is closed."""
        if self._closed:
            raise RuntimeError("feedback stream is closed")
        # fault point online.ingest: an injected error refuses this chunk
        # (producer-visible), delay_s stalls intake
        faults.inject("online.ingest", context={"rows": len(chunk)})
        ts = self._now() if ts is None else ts
        with self._cond:
            self._buf.append((ts, chunk))
            if len(self._buf) > self._max_chunks:
                self._buf.popleft()  # freshest-wins: shed the oldest
                self.dropped += 1
                _M_DROPPED.inc()
            self.ingested += len(chunk)
            _M_DEPTH.set(len(self._buf))
            self._cond.notify()
        _M_INGESTED.inc(len(chunk))
        _M_CHUNKS.inc()
        return len(chunk)

    # -- consumption ---------------------------------------------------------

    def depth(self) -> int:
        with self._cond:
            return len(self._buf)

    def poll(self, timeout_s: float = 0.25) -> Optional[tuple]:
        """Next ``(ingest_ts, DataFrame)`` micro-batch, or None.

        Buffered (pushed) chunks win; otherwise a pull source is drawn
        from (stamped at draw time — that IS its ingest into the
        system); otherwise block up to ``timeout_s`` for a push."""
        with self._cond:
            if self._buf:
                item = self._buf.popleft()
                _M_DEPTH.set(len(self._buf))
                return item
        if self._source is not None and not self._exhausted:
            if self._iter is None:
                self._iter = self._source()
            # fault point BEFORE the draw: an injected refusal leaves the
            # chunk in the iterator (retried next poll), matching the
            # push path where the producer keeps the refused chunk —
            # firing after next() would silently lose examples
            faults.inject("online.ingest", context={"mode": "pull"})
            try:
                chunk = next(self._iter)
            except StopIteration:
                self._exhausted = True
                return None
            self.ingested += len(chunk)
            _M_INGESTED.inc(len(chunk))
            _M_CHUNKS.inc()
            return (self._now(), chunk)
        with self._cond:
            if not self._buf and timeout_s > 0:
                self._cond.wait(timeout_s)
            if self._buf:
                item = self._buf.popleft()
                _M_DEPTH.set(len(self._buf))
                return item
        return None

    @property
    def exhausted(self) -> bool:
        """A pull source ran dry (push streams never exhaust)."""
        return self._exhausted and self.depth() == 0

    # -- HTTP ingest endpoint ------------------------------------------------

    def serve(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        name: str = "serving-online",
    ) -> Any:
        """Start the HTTP ingest ingress: ``POST /ingest`` with
        ``{"rows": [{...}, ...]}`` (or one bare row object) buffers a
        micro-batch; ``GET /health`` answers liveness; ``GET /metrics``
        is served inline by the WorkerServer machinery. Returns the
        :class:`ServiceInfo` (registered under ``name`` by the fleet
        wiring so ``fleet top`` and the deploy smoke can find the loop).
        """
        from mmlspark_tpu.serving.server import WorkerServer

        srv = WorkerServer(host=host, port=port, name=name)
        info = srv.start()
        self._ingress = srv
        self._router = threading.Thread(
            target=self._ingest_loop, name="online-ingest", daemon=True
        )
        self._router.start()
        return info

    def _ingest_loop(self) -> None:
        srv = self._ingress
        while not self._closed:
            reqs = srv.get_next_batch(max_n=64, timeout_s=0.25)
            for r in reqs:
                try:
                    self._ingest_one(r)
                except Exception as e:  # noqa: BLE001 — ingress must survive
                    _M_REFUSED.inc()
                    srv.reply_to(
                        r.id,
                        json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}
                        ).encode(),
                        503, _JSON,
                    )
            if reqs:
                srv.auto_commit()
        for r in srv.get_next_batch(max_n=1_000_000, timeout_s=0.0):
            srv.reply_to(r.id, b"ingest stopping", 503)

    def _ingest_one(self, r: Any) -> None:
        path = r.path.split("?", 1)[0]
        if path in ("/health", "/healthz") and r.method == "GET":
            self._ingress.reply_to(
                r.id,
                json.dumps(
                    {"status": "ok", "buffered_chunks": self.depth()}
                ).encode(),
                200, _JSON,
            )
            return
        if path != "/ingest" or r.method != "POST":
            self._ingress.reply_to(
                r.id, b'{"error": "POST /ingest"}', 404, _JSON
            )
            return
        body = json.loads(r.body) if r.body else {}
        rows = body["rows"] if isinstance(body, dict) and "rows" in body \
            else [body]
        if (
            not isinstance(rows, list) or not rows
            or not all(isinstance(x, dict) for x in rows)
        ):
            raise ValueError("rows must be a non-empty list of objects")
        n = self.push(DataFrame.from_rows(rows))
        self._ingress.reply_to(
            r.id,
            json.dumps(
                {"accepted": n, "buffered_chunks": self.depth()}
            ).encode(),
            200, _JSON,
        )

    def close(self) -> None:
        self._closed = True
        if self._router is not None:
            self._router.join(5.0)
        if self._ingress is not None:
            self._ingress.stop()
        with self._cond:
            self._cond.notify_all()


__all__ = ["FeedbackStream"]
