"""Launch the north-star streaming workload: N rows of 224x224 images
through ImageFeaturizer without ever materializing the dataset.

BASELINE.md's headline config is ResNet-50 featurization over a 1M-row
DataFrame (~150 GB of pixels — far beyond host memory); the reference
streams partitions from disk (io/binary/BinaryFileFormat.scala:112-149).
Here the source is a StreamingDataFrame of synthetic image chunks, so the
full-size run is LAUNCHABLE on any host and the featurize path sees
exactly the production shapes.

  PYTHONPATH=. python tools/northstar_stream.py                 # 1M rows
  PYTHONPATH=. JAX_PLATFORMS=cpu python tools/northstar_stream.py \
      --rows 512 --chunk 128 --size 32 --model ResNet8_Digits   # smoke
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.io.stream import StreamingDataFrame
from mmlspark_tpu.models import ImageFeaturizer


def run(rows: int, chunk: int, size: int, model: str, batch: int) -> dict:
    n_chunks = (rows + chunk - 1) // chunk

    def make_chunk(i: int) -> DataFrame:
        # deterministic per-chunk synthesis — nothing persists across chunks
        rng = np.random.default_rng(i)
        n = min(chunk, rows - i * chunk)
        imgs = rng.integers(0, 255, size=(n, size, size, 3), dtype=np.uint8)
        return DataFrame.from_dict({"image": imgs})

    stream = StreamingDataFrame.from_generator(make_chunk, num_chunks=n_chunks)
    feat = ImageFeaturizer(
        input_col="image", output_col="features",
        model_name=model, batch_size=batch, image_size=size,
    )
    t0 = time.perf_counter()
    done = [0]

    def sink(out: DataFrame) -> None:
        _ = out["features"]  # materialize the chunk's features, then drop
        done[0] += len(out)
        if done[0] % (chunk * 8) < chunk:
            dt = time.perf_counter() - t0
            print(f"  {done[0]}/{rows} rows  {done[0] / dt:.1f} img/s", flush=True)

    total = stream.transform(feat).foreach_chunk(sink)
    dt = time.perf_counter() - t0
    return {"rows": total, "seconds": round(dt, 2), "images_per_sec": round(total / dt, 2)}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--chunk", type=int, default=2048)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--model", default="ResNet50")
    ap.add_argument("--batch", type=int, default=256)
    args = ap.parse_args()
    print(run(args.rows, args.chunk, args.size, args.model, args.batch))


if __name__ == "__main__":
    main()
