"""One-shot TPU validation of the round-5 perf paths.

Run on a machine with the TPU backend available (takes the single-chip
claim; don't run concurrently with another TPU process):

    python tools/tpu_validation.py            # prints one JSON line

Measures, at the bench head-to-head shapes (100k x 32, 50 iters, 63
leaves): the data-partitioned lossguide grower vs the masked grower vs
depthwise vs sklearn wall-clock, plus the single-plane histogram rate.
All timings use host fetches (block_until_ready resolves early over a
remote relay).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     ".jax_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    devs = jax.devices()
    out: dict = {"platform": devs[0].platform, "n_dev": len(devs)}

    from mmlspark_tpu.models.gbdt import TrainConfig, train

    rng = np.random.default_rng(7)
    n, d, iters, leaves = 100_000, 32, 50, 63
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (np.sin(2 * x[:, 0]) + x[:, 1] * x[:, 2] > 0).astype(np.float64)

    def best2(cfg: TrainConfig) -> float:
        train(x, y, cfg)  # warm at the exact shape + iteration count
        times = []
        for _ in range(2):
            t0 = time.perf_counter()
            train(x, y, cfg)
            times.append(time.perf_counter() - t0)
        return min(times)

    cfg = TrainConfig(objective="binary", num_iterations=iters,
                      num_leaves=leaves, min_data_in_leaf=20, seed=7)
    os.environ["MMLSPARK_TPU_GBDT_PARTITION"] = "1"
    out["lossguide_partitioned_s"] = round(best2(cfg), 2)
    os.environ["MMLSPARK_TPU_GBDT_PARTITION"] = "0"
    out["lossguide_masked_s"] = round(best2(cfg), 2)
    os.environ.pop("MMLSPARK_TPU_GBDT_PARTITION", None)
    cfgd = TrainConfig(objective="binary", num_iterations=iters,
                       num_leaves=leaves, min_data_in_leaf=20, seed=7,
                       growth_policy="depthwise")
    out["depthwise_s"] = round(best2(cfgd), 2)
    # sibling-subtraction A/B: the depthwise default histograms only the
    # right child of each pair (left = parent - right), halving the
    # multi-plane kernel's MXU width per level
    os.environ["MMLSPARK_TPU_GBDT_SIBLING"] = "0"
    out["depthwise_no_sibling_s"] = round(best2(cfgd), 2)
    os.environ.pop("MMLSPARK_TPU_GBDT_SIBLING", None)
    out["sibling_speedup"] = round(
        out["depthwise_no_sibling_s"] / out["depthwise_s"], 2
    )
    # vector-split A/B, both sides pinned explicitly (the backend default
    # would silently compare sequential vs sequential off-TPU)
    os.environ["MMLSPARK_TPU_GBDT_VECTOR_SPLIT"] = "1"
    out["depthwise_vec_split_s"] = round(best2(cfgd), 2)
    os.environ["MMLSPARK_TPU_GBDT_VECTOR_SPLIT"] = "0"
    out["depthwise_seq_split_s"] = round(best2(cfgd), 2)
    os.environ.pop("MMLSPARK_TPU_GBDT_VECTOR_SPLIT", None)
    out["vector_split_speedup"] = round(
        out["depthwise_seq_split_s"] / out["depthwise_vec_split_s"], 2
    )
    # masked/partitioned ratio needs only the TPU timings — compute it
    # before (and regardless of) the sklearn head-to-head below
    out["partitioned_over_masked"] = round(
        out["lossguide_partitioned_s"] / out["lossguide_masked_s"], 2
    )
    try:
        from sklearn.ensemble import HistGradientBoostingClassifier

        sk = HistGradientBoostingClassifier(
            max_iter=iters, max_leaf_nodes=leaves, min_samples_leaf=20,
            learning_rate=0.1, early_stopping=False, random_state=7,
        )
        sk_times = []
        for _ in range(2):  # min-of-2, same treatment as the TPU side
            t0 = time.perf_counter()
            sk.fit(x, y)
            sk_times.append(time.perf_counter() - t0)
        out["sklearn_s"] = round(min(sk_times), 2)
        out["masked_vs_sklearn"] = round(
            out["sklearn_s"] / out["lossguide_masked_s"], 2
        )
        out["depthwise_vs_sklearn"] = round(
            out["sklearn_s"] / out["depthwise_s"], 2
        )
        out["partitioned_vs_sklearn"] = round(
            out["sklearn_s"] / out["lossguide_partitioned_s"], 2
        )
    except ImportError:
        pass
    print(json.dumps(out))


if __name__ == "__main__":
    main()
