#!/bin/bash
# Probe the chip every ~10 min; on success capture the full bench +
# validation as builder evidence, then exit 0. Exit 1 after ~2h of
# failed probes. All chip users exit cleanly (probe self-bounds; the
# bench parent traps SIGTERM) — nothing here SIGKILLs a chip holder.
cd /root/repo
for i in $(seq 1 12); do
  echo "[watch] probe $i $(date +%T)"
  python tools/tpu_probe.py 240 > /tmp/probe_last.json 2>&1
  if grep -q '"ok": true' /tmp/probe_last.json; then
    echo "[watch] CHIP UP $(date +%T)"; cat /tmp/probe_last.json
    rm -f bench_partial.json
    timeout 2400 python bench.py > /tmp/bench_tpu_r05.json 2>/tmp/bench_tpu_r05.err
    echo "[watch] bench rc=$? $(date +%T)"
    tail -c 400 /tmp/bench_tpu_r05.json
    PYTHONPATH=/root/repo:/root/.axon_site timeout 580 python tools/tpu_validation.py \
      > /tmp/tpu_validation_r05b.json 2>&1
    echo "[watch] validation rc=$? $(date +%T)"
    exit 0
  fi
  tail -1 /tmp/probe_last.json
  sleep 600
done
echo "[watch] no chip after $i probes"
exit 1
