"""Train the packaged zoo backbone from committed data.

The reference ships a zoo of trained CNTK models fetched from a remote
repository (downloader/Schema.scala:54-66, ModelDownloader.scala:210-276).
This build is egress-free, so the zoo's trained entry is produced HERE —
a compact ResNet8 trained on the committed UCI digits dataset
(tests/resources/data/digits.csv, 1797 8x8 grayscale digits) — and the
resulting checkpoint + schema are committed under
mmlspark_tpu/downloader/builtin/.

Reproduce:  PYTHONPATH=. JAX_PLATFORMS=cpu python tools/train_zoo_backbone.py
Runtime:    ~2 min on CPU. Deterministic given the fixed seed.
"""

from __future__ import annotations

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from mmlspark_tpu.downloader.zoo import PACKAGED_DIR, ModelDownloader, ModelSchema
from mmlspark_tpu.models.resnet import resnet8

SEED = 7
IMAGE_SIZE = 32
EPOCHS = 40
BATCH = 128
# deterministic split: last 297 rows held out, never trained on (the
# transfer-learning test evaluates its linear heads there)
N_TRAIN = 1500


def load_digits() -> tuple:
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "..", "tests", "resources", "data", "digits.csv",
    )
    raw = np.genfromtxt(path, delimiter=",", skip_header=1)
    x, y = raw[:, :64].reshape(-1, 8, 8), raw[:, 64].astype(np.int32)
    return x, y


def digits_to_images(x8: np.ndarray, size: int = IMAGE_SIZE) -> np.ndarray:
    """8x8 [0,16] grayscale -> (n, size, size, 3) float32 NORMALIZED with
    the exact preprocessing ImageFeaturizer applies (ops/image.normalize:
    /255 then ImageNet mean/std) so the committed weights see identical
    inputs through the featurizer path."""
    from mmlspark_tpu.ops.image import normalize

    rep = size // 8
    img = np.kron(x8 / 16.0, np.ones((rep, rep)))  # nearest-neighbor upsample
    rgb255 = np.repeat(img[..., None], 3, axis=-1).astype(np.float32) * 255.0
    return np.asarray(normalize(jnp.asarray(rgb255)), np.float32)


def main() -> None:
    x8, y = load_digits()
    imgs = digits_to_images(x8)
    xtr, ytr = imgs[:N_TRAIN], y[:N_TRAIN]

    model = resnet8(num_classes=10, small_inputs=True)
    variables = model.init(jax.random.PRNGKey(SEED), xtr[:1], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    tx = optax.adamw(3e-3, weight_decay=1e-4)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, batch_stats, opt_state, xb, yb):
        def loss_fn(p):
            out, mut = model.apply(
                {"params": p, "batch_stats": batch_stats},
                xb, train=True, mutable=["batch_stats"],
            )
            logits = out["logits"]
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()
            return loss, (mut["batch_stats"], logits)

        (loss, (bs, logits)), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        acc = (logits.argmax(-1) == yb).mean()
        return params, bs, opt_state, loss, acc

    rng = np.random.default_rng(SEED)
    n = len(xtr)
    for epoch in range(EPOCHS):
        order = rng.permutation(n)
        losses, accs = [], []
        for i in range(0, n - BATCH + 1, BATCH):
            idx = order[i : i + BATCH]
            params, batch_stats, opt_state, loss, acc = step(
                params, batch_stats, opt_state, jnp.asarray(xtr[idx]), jnp.asarray(ytr[idx])
            )
            losses.append(float(loss))
            accs.append(float(acc))
        print(f"epoch {epoch}: loss={np.mean(losses):.4f} acc={np.mean(accs):.4f}")

    # eval on the held-out tail (not used for model selection — reporting only)
    out = model.apply(
        {"params": params, "batch_stats": batch_stats}, jnp.asarray(imgs[N_TRAIN:]),
        train=False,
    )
    test_acc = float((np.asarray(out["logits"]).argmax(-1) == y[N_TRAIN:]).mean())
    print(f"held-out acc: {test_acc:.4f}")

    schema = ModelSchema(
        name="ResNet8_Digits",
        variant="ResNet8",
        num_classes=10,
        image_size=IMAGE_SIZE,
        small_inputs=True,
        layer_names=["logits", "pool", "layer3", "layer2", "layer1", "stem"],
        seed=SEED,
    )
    repo = ModelDownloader(repo_dir=PACKAGED_DIR)
    repo.register(schema, {"params": params, "batch_stats": batch_stats})
    print(f"wrote {PACKAGED_DIR}/ResNet8_Digits.msgpack sha256={schema.sha256}")


if __name__ == "__main__":
    main()
