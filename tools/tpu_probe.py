"""Bounded TPU availability probe.

Checks whether the axon-tunneled chip will initialize within a budget.
Exits CLEANLY (interpreter teardown -> PJRT client release handshake)
whenever init succeeds or fails fast. When init hangs inside the native
PJRT/gRPC call, NOTHING can unwind it — a Python-level SIGALRM handler
only runs between bytecodes, so the in-process alarm never fires while
the C call blocks. For that case a daemon thread hard-exits the process
at budget + 10 s so no external SIGKILL is needed; the claim (if one was
queued) is stranded either way — that outcome is inherent to a hung
init, not a probe defect. Callers should rely on the probe's own exit
and never kill it externally.

    python tools/tpu_probe.py [budget_seconds=120]

Prints one JSON line {"ok": bool, "init_s": float | null, "error": str}.
Exit codes: 0 = chip usable, 1 = init failed fast, 2 = init hung.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time


def main() -> int:
    budget = int(sys.argv[1]) if len(sys.argv) > 1 else 120

    def _hard_exit() -> None:
        # last resort for an init hung in native code: report, then exit
        # without teardown (teardown would block on the same hung client)
        print(json.dumps({
            "ok": False, "init_s": None,
            "error": f"backend init still blocked at {budget + 10}s; "
                     "hard exit (claim may be stranded upstream)",
        }), flush=True)
        os._exit(2)

    watchdog = threading.Timer(budget + 10, _hard_exit)
    watchdog.daemon = True
    watchdog.start()

    class _Timeout(Exception):
        pass

    def _raise(signum, frame):
        raise _Timeout(f"no backend init within {budget}s")

    # the alarm catches the slow-but-interpretable case (init returns to
    # Python between retries); the watchdog thread catches the hard hang
    signal.signal(signal.SIGALRM, _raise)
    signal.alarm(budget)
    t0 = time.time()
    try:
        import jax
        import jax.numpy as jnp

        devs = jax.devices()
        # one tiny dispatch proves the claim is usable, not just granted
        float((jnp.ones((8, 8)) @ jnp.ones((8, 8))).sum())
        signal.alarm(0)
        watchdog.cancel()
        print(json.dumps({
            "ok": True,
            "init_s": round(time.time() - t0, 1),
            "devices": [str(d) for d in devs],
        }))
        return 0
    except _Timeout as e:
        signal.alarm(0)
        watchdog.cancel()
        print(json.dumps({"ok": False, "init_s": None, "error": str(e)}))
        return 1
    except Exception as e:  # noqa: BLE001 — report, never crash
        signal.alarm(0)
        watchdog.cancel()
        print(json.dumps({"ok": False, "init_s": None,
                          "error": str(e)[:300]}))
        return 1


if __name__ == "__main__":
    sys.exit(main())
