"""Generate the sample notebooks under notebooks/samples/.

The reference ships ~25 runnable sample notebooks exercised end-to-end by
its CI (notebooks/samples/*.ipynb, nbtest/NotebookTests.scala:16-51). The
TPU rebuild keeps the same idea: every notebook here is executed by
tests/test_notebooks.py on every run. Notebooks are generated from this
script so content stays reviewable and regenerable:

    python tools/make_notebooks.py
"""

from __future__ import annotations

import json
import os

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "notebooks", "samples")


def nb(cells: list) -> dict:
    return {
        "cells": [
            {
                "cell_type": kind,
                "metadata": {},
                **(
                    {"source": src.splitlines(keepends=True)}
                    if kind == "markdown"
                    else {
                        "source": src.splitlines(keepends=True),
                        "outputs": [],
                        "execution_count": None,
                    }
                ),
            }
            for kind, src in cells
        ],
        "metadata": {
            "kernelspec": {"display_name": "Python 3", "language": "python",
                           "name": "python3"},
            "language_info": {"name": "python", "version": "3"},
        },
        "nbformat": 4,
        "nbformat_minor": 5,
    }


# every notebook resolves committed datasets relative to the repo root (the
# runner test sets cwd to the repo root, like the reference's nbtest runs
# notebooks from the workspace root)
_DATA = (
    "import os\n"
    "data_dir = os.path.join(os.getcwd(), 'tests', 'resources', 'data')\n"
)

NOTEBOOKS = {
    # reference: Classification - Adult Census.ipynb (TrainClassifier flow)
    "Classification - Breast Cancer with GBDT.ipynb": [
        ("markdown",
         "# Classification with the GBDT (LightGBM equivalent)\n\n"
         "The reference's *Classification - Adult Census* flow: load a real\n"
         "tabular dataset, train a boosted-tree classifier, and compute a\n"
         "full metrics DataFrame with `ComputeModelStatistics`."),
        ("code",
         _DATA +
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.io.csv import read_csv\n\n"
         "raw = read_csv(os.path.join(data_dir, 'breast_cancer.csv'))\n"
         "feat_cols = [c for c in raw.columns if c != 'label']\n"
         "x = np.stack([np.asarray(raw[c], np.float64) for c in feat_cols], 1)\n"
         "df = DataFrame.from_dict({'features': x.astype(np.float32),\n"
         "                          'label': np.asarray(raw['label'])})\n"
         "len(df.columns), df.count()"),
        ("code",
         "from mmlspark_tpu.models.gbdt import LightGBMClassifier\n\n"
         "model = LightGBMClassifier(num_iterations=30, num_leaves=31,\n"
         "                           boosting_type='goss').fit(df)\n"
         "scored = model.transform(df)\n"
         "scored['prediction'][:10]"),
        ("code",
         "from mmlspark_tpu.train import ComputeModelStatistics\n\n"
         "stats = ComputeModelStatistics(\n"
         "    label_col='label', scored_probabilities_col='probability'\n"
         ").transform(scored)\n"
         "auc = float(stats['AUC'][0])\n"
         "assert auc > 0.98, auc\n"
         "print('AUC', auc)"),
    ],
    # reference: Classification - Twitter Sentiment with Vowpal Wabbit.ipynb
    "Classification - Text with Vowpal Wabbit.ipynb": [
        ("markdown",
         "# Online text classification with the VW-equivalent learner\n\n"
         "Hashed sparse text features -> device SGD with per-pass weight\n"
         "averaging (the spanning-tree allreduce analogue). Mirrors the\n"
         "reference's Twitter-sentiment VW notebook."),
        ("code",
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.vw import VowpalWabbitClassifier, VowpalWabbitFeaturizer\n\n"
         "rng = np.random.default_rng(0)\n"
         "pos = 'great fantastic love wonderful best amazing superb'.split()\n"
         "neg = 'terrible awful hate worst broken horrible useless'.split()\n"
         "texts, labels = [], []\n"
         "for i in range(400):\n"
         "    words = rng.choice(pos if i % 2 == 0 else neg, size=4)\n"
         "    texts.append(' '.join(words))\n"
         "    labels.append(float(i % 2 == 0))\n"
         "labels = np.array(labels)\n"
         "df = DataFrame.from_dict({'text': np.array(texts, object), 'label': labels})\n"
         "fdf = VowpalWabbitFeaturizer(input_cols=['text'],\n"
         "                             output_col='features').transform(df)"),
        ("code",
         "model = VowpalWabbitClassifier(num_passes=3).fit(fdf)\n"
         "pred = model.transform(fdf)['prediction']\n"
         "acc = float((pred == labels).mean())\n"
         "assert acc > 0.95, acc\n"
         "print('accuracy', acc)"),
        ("code",
         "# per-partition training diagnostics (TrainingStats analogue)\n"
         "model.get_performance_statistics().to_dict()"),
    ],
    # reference: DeepLearning - Flowers.ipynb (transfer learning)
    "DeepLearning - Transfer Learning with ImageFeaturizer.ipynb": [
        ("markdown",
         "# Transfer learning with ImageFeaturizer\n\n"
         "The reference's flagship flow (*DeepLearning - Flowers*): a\n"
         "headless zoo backbone featurizes images, a cheap linear head\n"
         "trains on top. The packaged `ResNet8_Digits` checkpoint ships\n"
         "TRAINED weights, so features carry real semantic content."),
        ("code",
         _DATA +
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.io.csv import read_csv\n\n"
         "raw = read_csv(os.path.join(data_dir, 'digits.csv'))\n"
         "feat_cols = [c for c in raw.columns if c != 'label']\n"
         "x = np.stack([np.asarray(raw[c], np.float64) for c in feat_cols], 1)\n"
         "imgs = np.repeat((x.reshape(-1, 8, 8, 1) * (255 / 16)).astype(np.uint8),\n"
         "                 3, axis=-1)  # grayscale -> RGB\n"
         "y = np.asarray(raw['label'])\n"
         "df = DataFrame.from_dict({'image': imgs, 'label': y})\n"
         "imgs.shape"),
        ("code",
         "from mmlspark_tpu.core.pipeline import Pipeline\n"
         "from mmlspark_tpu.models import ImageFeaturizer\n"
         "from mmlspark_tpu.models.linear import LogisticRegression\n\n"
         "pipe = Pipeline(stages=[\n"
         "    ImageFeaturizer(input_col='image', output_col='features',\n"
         "                    model_name='ResNet8_Digits', cut_output_layers=1),\n"
         "    LogisticRegression(max_iter=200),\n"
         "])\n"
         "model = pipe.fit(df)\n"
         "pred = model.transform(df)['prediction']\n"
         "acc = float((pred == y).mean())\n"
         "assert acc > 0.9, acc\n"
         "print('transfer-learning accuracy', acc)"),
        ("markdown",
         "## Natural-image transfer with the RotNet-pretrained backbone\n\n"
         "`ResNet18_Patches` ships weights pretrained SELF-SUPERVISED\n"
         "(rotation prediction) on natural photograph patches\n"
         "(tools/train_patch_backbone.py). With a handful of labels from a\n"
         "never-seen image region, its features beat a random-init backbone\n"
         "of the identical architecture."),
        ("code",
         "from sklearn.datasets import load_sample_images\n"
         "from sklearn.linear_model import LogisticRegression as SkLR\n\n"
         "images = load_sample_images().images\n"
         "def patches(n, seed):\n"
         "    r = np.random.default_rng(seed)\n"
         "    xs = np.empty((n, 32, 32, 3), np.uint8); ys = np.empty(n, np.int64)\n"
         "    for i in range(n):\n"
         "        which = int(r.integers(2)); img = images[which]\n"
         "        h, w = img.shape[:2]\n"
         "        x0 = int(r.integers(int(w*0.75), w-32))  # held-out strip\n"
         "        band = int(r.integers(4)); bh = h//4\n"
         "        y0 = band*bh + int(r.integers(0, max(bh-32, 1)))\n"
         "        xs[i] = img[y0:y0+32, x0:x0+32]; ys[i] = which*4 + band\n"
         "    return xs, ys\n"
         "xtr, ytr = patches(160, 1)\n"
         "xte, yte = patches(400, 2)"),
        ("code",
         "feat = ImageFeaturizer(input_col='image', output_col='features',\n"
         "                       model_name='ResNet18_Patches',\n"
         "                       cut_output_layers=1, image_size=32)\n"
         "ftr = np.stack(feat.transform(DataFrame.from_dict({'image': xtr}))['features'])\n"
         "fte = np.stack(feat.transform(DataFrame.from_dict({'image': xte}))['features'])\n"
         "mu, sd = ftr.mean(0), ftr.std(0) + 1e-6\n"
         "probe = SkLR(max_iter=3000).fit((ftr-mu)/sd, ytr)\n"
         "acc = probe.score((fte-mu)/sd, yte)\n"
         "print('8-way patch localization from 160 labels:', round(acc, 3))\n"
         "assert acc > 0.8, acc"),
    ],
    # reference: Interpretability - LIME explainers
    "Interpretability - Tabular LIME.ipynb": [
        ("markdown",
         "# Model interpretability with Tabular LIME\n\n"
         "Sample perturbation masks, score them with the trained model, and\n"
         "solve a local lasso per row (vmapped ISTA on device) — the\n"
         "reference's LIME flow."),
        ("code",
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.lime import TabularLIME\n"
         "from mmlspark_tpu.models.gbdt import LightGBMClassifier\n\n"
         "rng = np.random.default_rng(1)\n"
         "x = rng.normal(size=(400, 6)).astype(np.float32)\n"
         "y = (x[:, 0] > 0).astype(np.float64)  # only feature 0 matters\n"
         "df = DataFrame.from_dict({'features': x, 'label': y})\n"
         "model = LightGBMClassifier(num_iterations=20).fit(df)"),
        ("code",
         "limed = TabularLIME(input_col='features', model=model,\n"
         "                    n_samples=512, seed=0).fit(df)\n"
         "explained = limed.transform(DataFrame.from_dict({'features': x[:16]}))\n"
         "w = np.stack([np.asarray(r) for r in explained['weights']])\n"
         "dominant = np.abs(w).argmax(axis=1)\n"
         "assert (dominant == 0).mean() > 0.8, dominant\n"
         "print('feature-0 dominance', float((dominant == 0).mean()))"),
    ],
    # reference: ModelInterpretation / Image Explainers notebook
    "Interpretability - Image LIME.ipynb": [
        ("markdown",
         "# Image interpretability with superpixel LIME\n\n"
         "SLIC superpixels (jitted), on/off mask sampling, model scoring\n"
         "and a per-image lasso attribute the prediction to regions — the\n"
         "reference's ImageLIME flow. The toy model below only looks at\n"
         "the top-left quadrant, and LIME finds exactly that."),
        ("code",
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.core.params import Param\n"
         "from mmlspark_tpu.core.pipeline import Transformer\n"
         "from mmlspark_tpu.lime import ImageLIME\n\n"
         "class QuadrantModel(Transformer):\n"
         "    input_col = Param('image column', default='image', type_=str)\n"
         "    def transform(self, df):\n"
         "        preds = np.array([\n"
         "            float(np.asarray(im)[:12, :12].mean())\n"
         "            for im in df[self.get('input_col')]\n"
         "        ])\n"
         "        return df.with_column('prediction', preds)\n\n"
         "imgs = np.empty(1, dtype=object)\n"
         "imgs[0] = np.full((24, 24, 3), 128.0, np.float32)\n"
         "df = DataFrame.from_dict({'image': imgs})\n"
         "out = ImageLIME(input_col='image', model=QuadrantModel(),\n"
         "                n_samples=256, cell_size=12.0,\n"
         "                regularization=0.0001, seed=3).transform(df)\n"
         "weights, labels = out['weights'][0], out['superpixels'][0]\n"
         "active = sorted(set(labels[:12, :12].ravel()))\n"
         "inactive = sorted(set(labels.ravel()) - set(active))\n"
         "w_active = max(weights[j] for j in active)\n"
         "w_inactive = max(abs(weights[j]) for j in inactive)\n"
         "print('active-quadrant weight', w_active, 'vs elsewhere', w_inactive)\n"
         "assert w_active > 5 * max(w_inactive, 1e-9)"),
    ],
    # reference: SparkServing - Deploying a Classifier.ipynb
    "Serving - Low Latency Model Endpoints.ipynb": [
        ("markdown",
         "# Low-latency model serving\n\n"
         "The Spark-Serving analogue: an HTTP ingress feeds fixed-shape\n"
         "minibatches to a jitted model; replies return on the same\n"
         "connection. Epoch queues + history replay give failure recovery."),
        ("code",
         "import json\n"
         "import http.client\n"
         "import numpy as np\n"
         "import jax, jax.numpy as jnp\n"
         "from mmlspark_tpu.serving.query import ServingQuery\n"
         "from mmlspark_tpu.serving.server import WorkerServer\n\n"
         "w = jnp.asarray(np.random.default_rng(2).normal(size=(8, 8)).astype(np.float32))\n"
         "model = jax.jit(lambda x: jnp.tanh(x @ w).sum(axis=-1))\n\n"
         "def handler(reqs):\n"
         "    x = np.stack([np.asarray(json.loads(r.body)['x'], np.float32)\n"
         "                  for r in reqs])\n"
         "    pad = -len(x) % 8\n"
         "    if pad:\n"
         "        x = np.pad(x, ((0, pad), (0, 0)))\n"
         "    y = np.asarray(model(jnp.asarray(x)))[: len(reqs)]\n"
         "    return {r.id: (200, json.dumps({'y': float(v)}).encode(), {})\n"
         "            for r, v in zip(reqs, y)}\n\n"
         "srv = WorkerServer()\n"
         "info = srv.start()\n"
         "q = ServingQuery(srv, handler, max_wait_ms=0).start()"),
        ("code",
         "conn = http.client.HTTPConnection('127.0.0.1', info.port, timeout=10)\n"
         "conn.request('POST', '/', body=json.dumps({'x': [0.1] * 8}))\n"
         "reply = json.loads(conn.getresponse().read())\n"
         "conn.close()\n"
         "q.stop(); srv.stop()\n"
         "assert 'y' in reply\n"
         "reply"),
    ],
    # reference: HyperParameterTuning - Fighting Breast Cancer.ipynb
    "HyperParameterTuning - Fighting Breast Cancer.ipynb": [
        ("markdown",
         "# Hyperparameter tuning\n\n"
         "`TuneHyperparameters` runs a randomized search with k-fold CV and\n"
         "a thread pool — the reference's AutoML notebook on the same\n"
         "dataset (UCI breast cancer)."),
        ("code",
         _DATA +
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.automl import (DiscreteHyperParam, HyperparamBuilder,\n"
         "                                 RangeHyperParam, TuneHyperparameters)\n"
         "from mmlspark_tpu.io.csv import read_csv\n"
         "from mmlspark_tpu.models.gbdt import LightGBMClassifier\n\n"
         "raw = read_csv(os.path.join(data_dir, 'breast_cancer.csv'))\n"
         "feat_cols = [c for c in raw.columns if c != 'label']\n"
         "x = np.stack([np.asarray(raw[c], np.float64) for c in feat_cols], 1)\n"
         "df = DataFrame.from_dict({'features': x.astype(np.float32),\n"
         "                          'label': np.asarray(raw['label'])})\n"
         "space = (HyperparamBuilder()\n"
         "         .add_hyperparam('num_leaves', DiscreteHyperParam([7, 15, 31]))\n"
         "         .add_hyperparam('learning_rate', RangeHyperParam(0.05, 0.3))\n"
         "         .build())\n"
         "tuner = TuneHyperparameters(\n"
         "    models=[LightGBMClassifier(num_iterations=15)], hyperparams=space,\n"
         "    evaluation_metric='AUC', number_of_folds=3, number_of_runs=4,\n"
         "    label_col='label', seed=0)\n"
         "best = tuner.fit(df)\n"
         "print('best AUC', best.get('best_metric'), best.get('best_params'))\n"
         "assert best.get('best_metric') > 0.97"),
    ],
    # reference: CyberML - Anomalous Access Detection.ipynb
    "CyberML - Anomalous Access Detection.ipynb": [
        ("markdown",
         "# CyberML: anomalous access detection\n\n"
         "Per-tenant collaborative filtering on user->resource access\n"
         "counts; cross-department accesses score anomalously high. The\n"
         "reference's python-only CyberML flow on its synthetic dataset."),
        ("code",
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.cyber import AccessAnomaly, synthetic_access_df\n\n"
         "df = synthetic_access_df(n_departments=3, users_per_dept=8,\n"
         "                         resources_per_dept=6, accesses_per_user=25,\n"
         "                         cross_dept_prob=0.0, seed=0)\n"
         "model = AccessAnomaly(rank=6, max_iter=10, seed=1).fit(df)"),
        ("code",
         "normal = DataFrame.from_dict({\n"
         "    'tenant': np.zeros(3, np.int64),\n"
         "    'user': np.array(['t0_d0_u0', 't0_d1_u1', 't0_d2_u2'], object),\n"
         "    'res': np.array(['t0_d0_r0', 't0_d1_r1', 't0_d2_r2'], object)})\n"
         "abnormal = DataFrame.from_dict({\n"
         "    'tenant': np.zeros(3, np.int64),\n"
         "    'user': np.array(['t0_d0_u0', 't0_d1_u1', 't0_d2_u2'], object),\n"
         "    'res': np.array(['t0_d1_r0', 't0_d2_r1', 't0_d0_r2'], object)})\n"
         "lo = float(np.mean(model.transform(normal)['anomaly_score']))\n"
         "hi = float(np.mean(model.transform(abnormal)['anomaly_score']))\n"
         "print('in-department', lo, 'cross-department', hi)\n"
         "assert hi > lo"),
    ],
    # reference: ConditionalKNN - Exploring Art Across Cultures.ipynb
    "ConditionalKNN - Nearest Neighbor Search.ipynb": [
        ("markdown",
         "# Nearest-neighbor search on device\n\n"
         "`KNN` runs brute-force max-inner-product top-k as one MXU matmul\n"
         "(`algorithm='balltree'` switches to the exact host ball tree) —\n"
         "the reference's art-exploration KNN flow."),
        ("code",
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.nn import KNN\n\n"
         "rng = np.random.default_rng(0)\n"
         "index = rng.normal(size=(500, 16)).astype(np.float32)\n"
         "index /= np.linalg.norm(index, axis=1, keepdims=True)\n"
         "names = np.array([f'item_{i}' for i in range(500)], object)\n"
         "idx_df = DataFrame.from_dict({'features': index, 'values': names})\n"
         "model = KNN(features_col='features', k=3).fit(idx_df)\n"
         "q = DataFrame.from_dict({'features': index[:5]})  # query = index rows\n"
         "out = model.transform(q)\n"
         "top = [m[0]['value'] for m in out['matches']]\n"
         "assert top == [f'item_{i}' for i in range(5)]  # self is the 1-NN\n"
         "out['matches'][0][:2]"),
    ],
    # reference: IsolationForest notebook (multivariate anomaly detection)
    "IsolationForest - Multivariate Anomaly Detection.ipynb": [
        ("markdown",
         "# Isolation-forest anomaly detection\n\n"
         "Host-side subsampled tree growth, branchless vectorized scoring on\n"
         "device — the native rebuild of the reference's isolation-forest\n"
         "wrapper."),
        ("code",
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.isolationforest import IsolationForest\n\n"
         "rng = np.random.default_rng(1)\n"
         "normal = rng.normal(0, 1, size=(500, 4)).astype(np.float32)\n"
         "outliers = rng.normal(6, 1, size=(10, 4)).astype(np.float32)\n"
         "x = np.concatenate([normal, outliers])\n"
         "df = DataFrame.from_dict({'features': x})\n"
         "model = IsolationForest(num_estimators=50, contamination=0.02,\n"
         "                        random_seed=3).fit(df)\n"
         "out = model.transform(df)\n"
         "scores = out['outlierScore']\n"
         "assert scores[-10:].mean() > scores[:-10].mean() + 0.1\n"
         "print('mean outlier score', float(scores[-10:].mean()),\n"
         "      'vs normal', float(scores[:-10].mean()))"),
    ],
    # reference: OpenCV - Pipeline Image Transformations.ipynb
    "OpenCV - Pipeline Image Transformations.ipynb": [
        ("markdown",
         "# Image transformation pipelines\n\n"
         "`ImageTransformer` chains resize/crop/flip/blur as ONE jitted\n"
         "device program over the whole batch — the OpenCV-stage-list\n"
         "notebook, without per-row JNI calls."),
        ("code",
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.image import ImageTransformer\n\n"
         "rng = np.random.default_rng(2)\n"
         "imgs = rng.integers(0, 255, size=(16, 64, 48, 3), dtype=np.uint8)\n"
         "df = DataFrame.from_dict({'image': imgs})\n"
         "it = (ImageTransformer(input_col='image', output_col='out')\n"
         "      .resize(32, 32)\n"
         "      .crop(4, 4, 24, 24)\n"
         "      .flip(1)\n"
         "      .blur(3, 1.0))\n"
         "out = it.transform(df)['out']\n"
         "assert out.shape == (16, 24, 24, 3), out.shape\n"
         "out.shape"),
    ],
    # reference: TextAnalytics - Amazon Book Reviews.ipynb
    "TextFeaturizer - Book Review Classification.ipynb": [
        ("markdown",
         "# Text featurization + classification\n\n"
         "`TextFeaturizer` tokenizes, n-grams and hashes text into fixed\n"
         "dimensions; a linear head classifies — the Amazon-book-reviews\n"
         "flow on synthetic review text."),
        ("code",
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.core.pipeline import Pipeline\n"
         "from mmlspark_tpu.featurize import TextFeaturizer\n"
         "from mmlspark_tpu.models.linear import LogisticRegression\n\n"
         "rng = np.random.default_rng(3)\n"
         "good = 'loved brilliant superb classic masterpiece'.split()\n"
         "bad = 'boring dreadful waste awful dull'.split()\n"
         "texts = []\n"
         "labels = []\n"
         "for i in range(300):\n"
         "    words = rng.choice(good if i % 2 == 0 else bad, size=5)\n"
         "    texts.append('This book was ' + ' '.join(words))\n"
         "    labels.append(float(i % 2 == 0))\n"
         "labels = np.array(labels)\n"
         "df = DataFrame.from_dict({'text': np.array(texts, object),\n"
         "                          'label': labels})\n"
         "pipe = Pipeline(stages=[\n"
         "    TextFeaturizer(input_col='text', output_col='features',\n"
         "                   num_features=1 << 12),\n"
         "    LogisticRegression(max_iter=150),\n"
         "])\n"
         "model = pipe.fit(df)\n"
         "acc = float((model.transform(df)['prediction'] == labels).mean())\n"
         "assert acc > 0.95, acc\n"
         "print('accuracy', acc)"),
    ],
    # reference: HttpOnSpark - Working with Arbitrary Web APIs.ipynb
    "HttpOnSpark - Parallelizing HTTP Requests.ipynb": [
        ("markdown",
         "# HTTP as a pipeline stage\n\n"
         "`SimpleHTTPTransformer` sends one async request per row with\n"
         "bounded concurrency, splits errors into a side column and parses\n"
         "JSON replies — the HTTP-on-Spark flow against a local service."),
        ("code",
         "import json\n"
         "import threading\n"
         "import numpy as np\n"
         "from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer\n\n"
         "class Echo(BaseHTTPRequestHandler):\n"
         "    def do_POST(self):\n"
         "        body = self.rfile.read(int(self.headers['Content-Length']))\n"
         "        out = json.dumps({'echo': json.loads(body)}).encode()\n"
         "        self.send_response(200)\n"
         "        self.send_header('Content-Type', 'application/json')\n"
         "        self.end_headers()\n"
         "        self.wfile.write(out)\n"
         "    def log_message(self, *a):\n"
         "        pass\n\n"
         "srv = ThreadingHTTPServer(('127.0.0.1', 0), Echo)\n"
         "threading.Thread(target=srv.serve_forever, daemon=True).start()\n"
         "url = f'http://127.0.0.1:{srv.server_port}/'"),
        ("code",
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.io.http_transformer import SimpleHTTPTransformer\n\n"
         "df = DataFrame.from_dict({'x': np.arange(8, dtype=np.int64)},\n"
         "                         num_partitions=2)\n"
         "t = SimpleHTTPTransformer(input_col='x', output_col='out',\n"
         "                          url=url, concurrency=4)\n"
         "out = t.transform(df)\n"
         "srv.shutdown()\n"
         "assert [o['echo'] for o in out['out']] == list(range(8))\n"
         "assert all(e is None for e in out['out_error'])\n"
         "out['out'][:3]"),
    ],
    # out-of-core processing (BinaryFileFormat streaming-read capability)
    "Streaming - Larger Than Memory DataFrames.ipynb": [
        ("markdown",
         "# Out-of-core pipelines with StreamingDataFrame\n\n"
         "Chunked sources stream partitions through fitted pipeline stages\n"
         "without materializing the dataset — the capability behind the\n"
         "reference's streaming binary/image file formats."),
        ("code",
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.io.stream import StreamingDataFrame\n"
         "from mmlspark_tpu.models.gbdt import LightGBMClassifier\n\n"
         "rng = np.random.default_rng(4)\n"
         "xtr = rng.normal(size=(500, 4)).astype(np.float32)\n"
         "ytr = (xtr[:, 0] > 0).astype(np.float64)\n"
         "model = LightGBMClassifier(num_iterations=10, num_leaves=7).fit(\n"
         "    DataFrame.from_dict({'features': xtr, 'label': ytr}))\n\n"
         "def make_chunk(i):\n"
         "    # 20 chunks stream through; the dataset is never resident\n"
         "    r = np.random.default_rng(1000 + i)\n"
         "    x = r.normal(size=(1000, 4)).astype(np.float32)\n"
         "    return DataFrame.from_dict({'features': x})\n\n"
         "sdf = StreamingDataFrame.from_generator(make_chunk, num_chunks=20)\n"
         "scored = sdf.transform(model)\n"
         "n = 0\n"
         "agree = 0\n"
         "for chunk in scored.iter_chunks():\n"
         "    pred = chunk['prediction']\n"
         "    agree += int((pred == (chunk['features'][:, 0] > 0)).sum())\n"
         "    n += len(pred)\n"
         "print('rows streamed', n, 'model/rule agreement', agree / n)\n"
         "assert n == 20_000 and agree / n > 0.95"),
    ],
    # reference: Recommendation - SAR.ipynb
    "Recommendation - SAR Item Recommender.ipynb": [
        ("markdown",
         "# SAR recommender\n\n"
         "Item-item co-occurrence similarity (jaccard) x time-decayed user\n"
         "affinity, scored as one device matmul — the reference's SAR\n"
         "notebook flow."),
        ("code",
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.recommendation import SAR\n\n"
         "rng = np.random.default_rng(3)\n"
         "n_users, n_items = 50, 30\n"
         "rows = []\n"
         "for u in range(n_users):\n"
         "    liked = rng.choice(n_items // 2, size=6, replace=False)\n"
         "    liked = liked * 2 + (u % 2)  # even users like even items\n"
         "    rows += [(u, int(i), 1.0, 1_600_000_000.0 + u) for i in liked]\n"
         "arr = np.array(rows)\n"
         "df = DataFrame.from_dict({'user_idx': arr[:, 0].astype(np.int64),\n"
         "                          'item_idx': arr[:, 1].astype(np.int64),\n"
         "                          'rating': arr[:, 2],\n"
         "                          'time': arr[:, 3]})\n"
         "model = SAR(time_col='time', similarity_function='jaccard',\n"
         "            support_threshold=1).fit(df)\n"
         "recs = model.recommend_for_all_users(k=5)\n"
         "users = np.asarray(recs['user_idx'])\n"
         "match = np.concatenate([np.asarray(r) % 2 == u % 2\n"
         "                        for u, r in zip(users, recs['recommendations'])])\n"
         "print('same-parity recommendation rate', float(match.mean()))\n"
         "assert match.mean() > 0.9"),
    ],
    # reference: Regression - Flight Delays.ipynb (TrainRegressor flow)
    "Regression - Flight Delays.ipynb": [
        ("markdown",
         "# Flight delay regression with TrainRegressor\n\n"
         "The reference's *Regression - Flight Delays* flow: a tabular\n"
         "flight table (carrier, origin, departure hour, distance) ->\n"
         "`TrainRegressor` promotion -> `ComputeModelStatistics`."),
        ("code",
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n\n"
         "rng = np.random.default_rng(0)\n"
         "n = 3000\n"
         "carrier = rng.integers(0, 8, n)      # airline id\n"
         "origin = rng.integers(0, 20, n)      # airport id\n"
         "dep_hour = rng.integers(5, 23, n)\n"
         "distance = rng.uniform(100, 2500, n)\n"
         "# delays grow with evening departures + congested airports\n"
         "delay = (2.0 * np.maximum(dep_hour - 15, 0)\n"
         "         + 0.8 * (origin % 5) + 0.3 * carrier\n"
         "         + rng.exponential(6.0, n))\n"
         "x = np.stack([carrier, origin, dep_hour, distance], 1).astype(np.float32)\n"
         "df = DataFrame.from_dict({'features': x, 'label': delay})\n"
         "df.count()"),
        ("code",
         "from mmlspark_tpu.models.gbdt import LightGBMRegressor\n"
         "from mmlspark_tpu.train import TrainRegressor\n\n"
         "model = TrainRegressor(\n"
         "    model=LightGBMRegressor(num_iterations=40, num_leaves=31),\n"
         "    label_col='label').fit(df)\n"
         "scored = model.transform(df)\n"
         "scored['prediction'][:5]"),
        ("code",
         "from mmlspark_tpu.train import ComputeModelStatistics\n\n"
         "stats = ComputeModelStatistics(label_col='label',\n"
         "                               scores_col='prediction').transform(scored)\n"
         "r2 = float(stats['R^2'][0]) if 'R^2' in stats.columns else None\n"
         "mse = float(stats['mean_squared_error'][0])\n"
         "base = float(((np.asarray(df['label']) - np.asarray(df['label']).mean()) ** 2).mean())\n"
         "assert mse < base * 0.5, (mse, base)\n"
         "print('MSE', round(mse, 2), 'vs variance', round(base, 2))"),
    ],
    # reference: Regression - Auto Imports.ipynb (CleanMissingData flow)
    "Regression - Auto Imports.ipynb": [
        ("markdown",
         "# Auto imports price regression\n\n"
         "The reference's *Regression - Auto Imports* flow: a messy autos\n"
         "table with missing values and categorical columns ->\n"
         "`CleanMissingData` -> GBDT with categorical splits."),
        ("code",
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n\n"
         "rng = np.random.default_rng(1)\n"
         "n = 2000\n"
         "make = rng.integers(0, 12, n).astype(np.float64)   # categorical\n"
         "horsepower = rng.uniform(48, 288, n)\n"
         "curb_weight = rng.uniform(1500, 4000, n)\n"
         "mpg = 60 - horsepower * 0.12 + rng.normal(0, 2, n)\n"
         "price = (horsepower * 80 + curb_weight * 2 + make * 500\n"
         "         + rng.normal(0, 900, n))\n"
         "# real-world mess: some horsepower/mpg readings are missing\n"
         "horsepower[rng.random(n) < 0.08] = np.nan\n"
         "mpg[rng.random(n) < 0.05] = np.nan\n"
         "df = DataFrame.from_dict({'make': make, 'horsepower': horsepower,\n"
         "                          'curb_weight': curb_weight, 'mpg': mpg,\n"
         "                          'price': price})\n"
         "df.count()"),
        ("code",
         "from mmlspark_tpu.featurize import CleanMissingData\n\n"
         "clean = CleanMissingData(input_cols=['horsepower', 'mpg'],\n"
         "                         output_cols=['horsepower', 'mpg'],\n"
         "                         cleaning_mode='Median').fit(df)\n"
         "cdf = clean.transform(df)\n"
         "assert not np.isnan(np.asarray(cdf['horsepower'])).any()"),
        ("code",
         "from mmlspark_tpu.models.gbdt import LightGBMRegressor\n\n"
         "x = np.stack([np.asarray(cdf[c], np.float32) for c in\n"
         "              ('make', 'horsepower', 'curb_weight', 'mpg')], 1)\n"
         "tdf = DataFrame.from_dict({'features': x,\n"
         "                           'label': np.asarray(cdf['price'])})\n"
         "model = LightGBMRegressor(num_iterations=40, num_leaves=31,\n"
         "                          categorical_slot_indexes=[0]).fit(tdf)\n"
         "pred = model.transform(tdf)['prediction']\n"
         "y = np.asarray(tdf['label'])\n"
         "r2 = 1 - ((pred - y) ** 2).mean() / y.var()\n"
         "assert r2 > 0.9, r2\n"
         "print('R^2', round(float(r2), 4))"),
    ],
    # reference: Regression - Vowpal Wabbit vs. LightGBM vs. Linear Regressor.ipynb
    "Regression - Vowpal Wabbit vs. LightGBM vs. Linear Regressor.ipynb": [
        ("markdown",
         "# Three regressors head-to-head\n\n"
         "The reference's comparison notebook on the diabetes dataset:\n"
         "VW-style online SGD vs GBDT vs closed-form linear regression,\n"
         "all through the same DataFrame API."),
        ("code",
         _DATA +
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.io.csv import read_csv\n\n"
         "raw = read_csv(os.path.join(data_dir, 'diabetes.csv'))\n"
         "feat_cols = [c for c in raw.columns if c != 'label']\n"
         "x = np.stack([np.asarray(raw[c], np.float64) for c in feat_cols], 1)\n"
         "y = np.asarray(raw['label'], np.float64)\n"
         "df = DataFrame.from_dict({'features': x.astype(np.float32), 'label': y})\n"
         "results = {}"),
        ("code",
         "from mmlspark_tpu.models.gbdt import LightGBMRegressor\n\n"
         "pred = LightGBMRegressor(num_iterations=60, num_leaves=15,\n"
         "                         min_data_in_leaf=10).fit(df).transform(df)['prediction']\n"
         "results['gbdt'] = float(((pred - y) ** 2).mean())"),
        ("code",
         "from mmlspark_tpu.vw import VowpalWabbitFeaturizer, VowpalWabbitRegressor\n\n"
         "fdf = VowpalWabbitFeaturizer(input_cols=['features'],\n"
         "                             num_bits=15).transform(df)\n"
         "# AdaGrad normalizes per-coordinate scale, but the wide target\n"
         "# range (~25-350) still wants a hot learning rate + many passes\n"
         "pred = VowpalWabbitRegressor(num_passes=200,\n"
         "                             learning_rate=20.0).fit(fdf).transform(fdf)['prediction']\n"
         "results['vw'] = float(((pred - y) ** 2).mean())"),
        ("code",
         "# closed-form ridge as the linear baseline\n"
         "xb = np.concatenate([x, np.ones((len(x), 1))], 1)\n"
         "w = np.linalg.solve(xb.T @ xb + 1e-3 * np.eye(xb.shape[1]), xb.T @ y)\n"
         "results['linear'] = float(((xb @ w - y) ** 2).mean())\n"
         "print({k: round(v, 1) for k, v in results.items()})\n"
         "assert results['gbdt'] < results['linear']  # trees beat linear here\n"
         "assert results['vw'] < y.var()              # vw beats the mean"),
    ],
    # reference: LightGBM - Quantile Regression for Drug Discovery.ipynb
    "LightGBM - Quantile Regression for Drug Discovery.ipynb": [
        ("markdown",
         "# Quantile regression for drug discovery\n\n"
         "The reference's flagship quantile notebook: predict an interval\n"
         "(10th/90th percentile) of a compound's activity instead of a\n"
         "point estimate — `objective='quantile'` with `alpha`."),
        ("code",
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n\n"
         "rng = np.random.default_rng(4)\n"
         "n, d = 4000, 12\n"
         "x = rng.normal(size=(n, d)).astype(np.float32)  # molecular descriptors\n"
         "activity = (x[:, 0] * 2 + x[:, 1] * x[:, 2]\n"
         "            + (0.5 + np.abs(x[:, 3])) * rng.normal(size=n))\n"
         "df = DataFrame.from_dict({'features': x, 'label': activity})"),
        ("code",
         "from mmlspark_tpu.models.gbdt import LightGBMRegressor\n\n"
         "bands = {}\n"
         "for alpha in (0.1, 0.9):\n"
         "    m = LightGBMRegressor(objective='quantile', alpha=alpha,\n"
         "                          num_iterations=40, num_leaves=15).fit(df)\n"
         "    bands[alpha] = m.transform(df)['prediction']"),
        ("code",
         "inside = ((activity >= bands[0.1]) & (activity <= bands[0.9])).mean()\n"
         "low_cover = (activity <= bands[0.1]).mean()\n"
         "print('80% interval covers', round(float(inside), 3))\n"
         "assert abs(inside - 0.8) < 0.08, inside\n"
         "assert abs(low_cover - 0.1) < 0.06, low_cover"),
    ],
    # reference: Vowpal Wabbit - Quantile Regression for Drug Discovery.ipynb
    "Vowpal Wabbit - Quantile Regression for Drug Discovery.ipynb": [
        ("markdown",
         "# VW quantile regression\n\n"
         "The same interval-prediction workload through the online\n"
         "learner: `loss_function='quantile'` with `quantile_tau`\n"
         "(`--loss_function quantile --quantile_tau` passthrough)."),
        ("code",
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.vw import VowpalWabbitFeaturizer, VowpalWabbitRegressor\n\n"
         "rng = np.random.default_rng(5)\n"
         "n, d = 3000, 8\n"
         "x = rng.normal(size=(n, d)).astype(np.float32)\n"
         "activity = x[:, 0] * 2 - x[:, 1] + rng.exponential(1.0, n)\n"
         "df = DataFrame.from_dict({'features': x, 'label': activity})\n"
         "fdf = VowpalWabbitFeaturizer(input_cols=['features'],\n"
         "                             num_bits=15).transform(df)"),
        ("code",
         "preds = {}\n"
         "for tau in (0.5, 0.9):\n"
         "    m = VowpalWabbitRegressor(\n"
         "        pass_through_args=f'--loss_function quantile --quantile_tau {tau}',\n"
         "        num_passes=30).fit(fdf)\n"
         "    preds[tau] = m.transform(fdf)['prediction']"),
        ("code",
         "for tau, p in preds.items():\n"
         "    cover = float((activity <= p).mean())\n"
         "    print(f'tau={tau}: empirical coverage {cover:.3f}')\n"
         "    assert abs(cover - tau) < 0.08, (tau, cover)"),
    ],
    # reference: deployment modes in docs/mmlspark-serving.md:93-160
    "Serving - Distributed Worker Fleet.ipynb": [
        ("markdown",
         "# Distributed serving: N workers behind one endpoint\n\n"
         "The reference's `DistributedHTTPSource` deployment: several\n"
         "serving workers register with a driver registry; a gateway\n"
         "round-robins client requests and re-dispatches to a live worker\n"
         "if one dies mid-request (zero lost requests)."),
        ("code",
         "import json\n"
         "import numpy as np\n"
         "from mmlspark_tpu.serving import (DriverRegistry, ServingGateway,\n"
         "                                  ServingQuery, WorkerServer)\n\n"
         "w = np.random.default_rng(0).normal(size=(8,)).astype(np.float32)\n\n"
         "def make_worker(tag):\n"
         "    srv = WorkerServer()\n"
         "    info = srv.start()\n"
         "    def handler(reqs):\n"
         "        out = {}\n"
         "        for r in reqs:\n"
         "            x = np.asarray(json.loads(r.body)['x'], np.float32)\n"
         "            y = float(x @ w)\n"
         "            out[r.id] = (200, json.dumps({'y': y, 'worker': tag}).encode(), {})\n"
         "        return out\n"
         "    q = ServingQuery(srv, handler, max_wait_ms=0).start()\n"
         "    return srv, q, info\n\n"
         "registry = DriverRegistry()\n"
         "workers = [make_worker(f'w{i}') for i in range(3)]\n"
         "for _, _, info in workers:\n"
         "    DriverRegistry.register(registry.url, info)\n"
         "len(registry.services('serving'))"),
        ("code",
         "import http.client\n\n"
         "gw = ServingGateway(registry_url=registry.url)\n"
         "ginfo = gw.start()\n"
         "def ask(x):\n"
         "    conn = http.client.HTTPConnection('127.0.0.1', ginfo.port, timeout=10)\n"
         "    conn.request('POST', '/', body=json.dumps({'x': x}))\n"
         "    resp = conn.getresponse(); body = json.loads(resp.read()); conn.close()\n"
         "    return body\n"
         "seen = {ask([float(i)] * 8)['worker'] for i in range(12)}\n"
         "print('workers serving:', sorted(seen))\n"
         "assert len(seen) == 3  # the load spreads over the fleet"),
        ("code",
         "# kill a worker: traffic keeps flowing through the survivors\n"
         "workers[0][1].stop(); workers[0][0].stop()\n"
         "answers = [ask([float(i)] * 8) for i in range(20)]\n"
         "assert all('y' in a for a in answers)  # zero lost requests\n"
         "assert {a['worker'] for a in answers} <= {'w1', 'w2'}\n"
         "gw.stop(); registry.stop()\n"
         "for srv, q, _ in workers[1:]:\n"
         "    q.stop(); srv.stop()\n"
         "print('fleet survived a worker death')"),
    ],
    # reference: LightGBM - Overview.ipynb (boosting modes + SHAP + native IO)
    "LightGBM - Overview.ipynb": [
        ("markdown",
         "# LightGBM-equivalent GBDT: a tour\n\n"
         "The reference's *LightGBM - Overview*: boosting modes (gbdt, goss,\n"
         "dart, rf), feature importances, SHAP explanations, and native\n"
         "text-format model exchange — all on the TPU grower."),
        ("code",
         _DATA +
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.io.csv import read_csv\n\n"
         "raw = read_csv(os.path.join(data_dir, 'breast_cancer.csv'))\n"
         "feat_cols = [c for c in raw.columns if c != 'label']\n"
         "x = np.stack([np.asarray(raw[c], np.float64) for c in feat_cols], 1)\n"
         "y = np.asarray(raw['label'])\n"
         "df = DataFrame.from_dict({'features': x.astype(np.float32), 'label': y})\n"
         "df.count()"),
        ("code",
         "from mmlspark_tpu.models.gbdt import LightGBMClassifier\n"
         "from mmlspark_tpu.core.metrics import binary_auc\n\n"
         "aucs = {}\n"
         "for mode in ('gbdt', 'goss', 'dart', 'rf'):\n"
         "    m = LightGBMClassifier(num_iterations=25, num_leaves=15,\n"
         "                           boosting_type=mode, seed=0).fit(df)\n"
         "    p = m.transform(df)['probability'][:, 1]\n"
         "    aucs[mode] = round(binary_auc(y, p), 4)\n"
         "print(aucs)\n"
         "assert min(aucs.values()) > 0.95, aucs"),
        ("code",
         "# feature importances + exact TreeSHAP on a handful of rows\n"
         "model = LightGBMClassifier(num_iterations=25, num_leaves=15).fit(df)\n"
         "imp = model.get_feature_importances('gain')\n"
         "shap = model.features_shap(x[:5].astype(np.float32))\n"
         "raw_pred = model.booster.predict_raw(x[:5].astype(np.float32))\n"
         "np.testing.assert_allclose(shap.sum(1), raw_pred, rtol=1e-4, atol=1e-4)\n"
         "print('top feature:', feat_cols[int(np.argmax(imp))])"),
        ("code",
         "# native LightGBM text format: save, reload, identical predictions\n"
         "import tempfile, os as _os\n"
         "with tempfile.TemporaryDirectory() as td:\n"
         "    path = _os.path.join(td, 'model.txt')\n"
         "    model.save_native_model(path)\n"
         "    from mmlspark_tpu.models.gbdt import LightGBMClassificationModel\n"
         "    back = LightGBMClassificationModel.load_native_model_from_file(path)\n"
         "    np.testing.assert_allclose(\n"
         "        back.booster.predict_raw(x[:20].astype(np.float32)),\n"
         "        model.booster.predict_raw(x[:20].astype(np.float32)),\n"
         "        rtol=1e-5, atol=1e-5)\n"
         "print('native round-trip ok')"),
    ],
    # reference: CognitiveServices - Overview.ipynb (against a local mock)
    "CognitiveServices - Overview.ipynb": [
        ("markdown",
         "# Cognitive-service enrichment in a pipeline\n\n"
         "The reference's *CognitiveServices - Overview* flow: DataFrame\n"
         "columns -> REST enrichment transformers (sentiment, language,\n"
         "key phrases) with per-row error columns. This notebook runs\n"
         "against a LOCAL mock service so it executes offline; point\n"
         "``url`` at a real endpoint + subscription key in production."),
        ("code",
         "import json, threading\n"
         "from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer\n\n"
         "class Mock(BaseHTTPRequestHandler):\n"
         "    def log_message(self, *a):\n"
         "        pass\n"
         "    def do_POST(self):\n"
         "        n = int(self.headers.get('Content-Length') or 0)\n"
         "        # the client MINIBATCHES: many documents arrive per POST,\n"
         "        # answered per id (the Text Analytics v3 wire format)\n"
         "        docs = json.loads(self.rfile.read(n))['documents']\n"
         "        path = self.path.split('?')[0]\n"
         "        if path.endswith('/sentiment'):\n"
         "            out = [{'id': d['id'], 'sentiment':\n"
         "                    'positive' if 'love' in d['text'] else 'negative'}\n"
         "                   for d in docs]\n"
         "        else:\n"
         "            out = [{'id': d['id'],\n"
         "                    'detectedLanguage': {'iso6391Name': 'en'}}\n"
         "                   for d in docs]\n"
         "        raw = json.dumps({'documents': out, 'errors': []}).encode()\n"
         "        self.send_response(200)\n"
         "        self.send_header('Content-Length', str(len(raw)))\n"
         "        self.end_headers()\n"
         "        self.wfile.write(raw)\n\n"
         "srv = ThreadingHTTPServer(('127.0.0.1', 0), Mock)\n"
         "threading.Thread(target=srv.serve_forever, daemon=True).start()\n"
         "url = f'http://127.0.0.1:{srv.server_port}'"),
        ("code",
         "import numpy as np\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.cognitive import TextSentiment\n\n"
         "df = DataFrame.from_dict({'text': np.array(\n"
         "    ['i love this tpu', 'terrible latency'], dtype=object)})\n"
         "scored = TextSentiment(url=url, output_col='sentiment',\n"
         "                       subscription_key='demo-key'\n"
         "                       ).set_col('text', 'text').transform(df)\n"
         "# outputs are TYPED records (schemas.SentimentDocument): attribute\n"
         "# access and dict-style both work, and the column carries schema\n"
         "# metadata for downstream consumers\n"
         "sentiments = [s.sentiment for s in scored['sentiment']]\n"
         "print(sentiments, scored.column_metadata('sentiment')['response_schema'])\n"
         "assert sentiments == ['positive', 'negative']\n"
         "srv.shutdown()"),
        ("markdown",
         "## Async services and the search sink\n\n"
         "`RecognizeText` speaks the service's ASYNC wire contract (202 +\n"
         "`Operation-Location`, then polling) with the polling riding the\n"
         "transformer's request thread pool; `SearchIndex` validates and\n"
         "creates indexes before `AzureSearchWriter` uploads documents."),
        ("code",
         "class AsyncMock(Mock):\n"
         "    polls = {}\n"
         "    indexes = []\n"
         "    def do_POST(self):\n"
         "        n = int(self.headers.get('Content-Length') or 0)\n"
         "        raw = self.rfile.read(n)\n"
         "        if '/recognizeText' in self.path:\n"
         "            self.send_response(202)\n"
         "            self.send_header('Operation-Location',\n"
         "                f'http://{self.headers.get(\"Host\")}/operations/op1')\n"
         "            self.send_header('Content-Length', '0')\n"
         "            self.end_headers()\n"
         "            return\n"
         "        if '/indexes' in self.path and '/docs' not in self.path:\n"
         "            type(self).indexes.append(json.loads(raw)['name'])\n"
         "            body = json.dumps({'ok': True}).encode()\n"
         "            self.send_response(201)\n"
         "        else:\n"
         "            docs = json.loads(raw)['value']\n"
         "            body = json.dumps({'value': [\n"
         "                {'key': str(i), 'status': True}\n"
         "                for i in range(len(docs))]}).encode()\n"
         "            self.send_response(200)\n"
         "        self.send_header('Content-Length', str(len(body)))\n"
         "        self.end_headers()\n"
         "        self.wfile.write(body)\n"
         "    def do_GET(self):\n"
         "        if '/operations/' in self.path:\n"
         "            n = type(self).polls.get('op1', 0) + 1\n"
         "            type(self).polls['op1'] = n\n"
         "            body = json.dumps({'status': 'Running'} if n < 2 else\n"
         "                {'status': 'Succeeded', 'recognitionResult':\n"
         "                 {'lines': [{'text': 'printed text'}]}}).encode()\n"
         "        else:\n"
         "            body = json.dumps({'value': [\n"
         "                {'name': x} for x in type(self).indexes]}).encode()\n"
         "        self.send_response(200)\n"
         "        self.send_header('Content-Length', str(len(body)))\n"
         "        self.end_headers()\n"
         "        self.wfile.write(body)\n\n"
         "asrv = ThreadingHTTPServer(('127.0.0.1', 0), AsyncMock)\n"
         "threading.Thread(target=asrv.serve_forever, daemon=True).start()\n"
         "aurl = f'http://127.0.0.1:{asrv.server_port}'"),
        ("code",
         "from mmlspark_tpu.cognitive import (AzureSearchWriter, RecognizeText,\n"
         "                                    SearchIndex)\n\n"
         "imgs = DataFrame.from_dict({'img': np.array(\n"
         "    ['http://x/a.png'], dtype=object)})\n"
         "rt = RecognizeText(url=aurl, output_col='rt', polling_delay_ms=20\n"
         "                   ).set_col('image_url', 'img').transform(imgs)\n"
         "rec = rt['rt'][0]\n"
         "print(rec.status, '->', rec.recognitionResult.lines[0].text)\n"
         "assert rec.recognitionResult.lines[0].text == 'printed text'\n\n"
         "SearchIndex.create_if_none_exists(aurl, {'name': 'notes', 'fields': [\n"
         "    {'name': 'id', 'type': 'Edm.String', 'key': True},\n"
         "    {'name': 'body', 'type': 'Edm.String', 'searchable': True}]})\n"
         "AzureSearchWriter.write(DataFrame.from_dict({\n"
         "    'id': np.array(['1'], dtype=object),\n"
         "    'body': np.array(['printed text'], dtype=object)}), aurl, 'notes')\n"
         "print('indexed into', SearchIndex.get_existing(aurl))\n"
         "asrv.shutdown()"),
    ],
    # zoo import flow: externally trained torchvision weights
    "DeepLearning - Importing Torch Checkpoints.ipynb": [
        ("markdown",
         "# Importing torchvision ResNet checkpoints\n\n"
         "The zoo accepts the de-facto standard serialized backbone format:\n"
         "a torchvision ResNet ``state_dict``. Externally trained weights\n"
         "(e.g. ImageNet ResNet-50) drop into `ImageFeaturizer` with their\n"
         "semantics intact — strided padding is matched to torch exactly.\n"
         "Here the 'external' model is a small torch network built inline."),
        ("code",
         "import numpy as np, tempfile, os, torch\n\n"
         "# a torchvision-layout ResNet-18 (conv1/bn1/layer1..4/fc keys)\n"
         "import sys\n"
         "sys.path.insert(0, os.path.join(os.getcwd(), 'tests'))\n"
         "from test_torch_import import _TorchResNet, _TorchBasic\n"
         "torch.manual_seed(0)\n"
         "tm = _TorchResNet(_TorchBasic, [2, 2, 2, 2], num_classes=10).eval()\n"
         "tmpdir = tempfile.mkdtemp()\n"
         "pth = os.path.join(tmpdir, 'resnet18.pth')\n"
         "torch.save(tm.state_dict(), pth)"),
        ("code",
         "from mmlspark_tpu.downloader import install_torch_checkpoint\n"
         "from mmlspark_tpu.downloader.zoo import ModelDownloader\n\n"
         "dl = ModelDownloader(repo_dir=os.path.join(tmpdir, 'zoo'))\n"
         "schema = install_torch_checkpoint(pth, name='ResNet18_External',\n"
         "                                  image_size=64, downloader=dl)\n"
         "print(schema.variant, schema.num_classes, 'torch_padding =', schema.torch_padding)"),
        ("code",
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.models import ImageFeaturizer\n"
         "from mmlspark_tpu.ops.image import normalize\n\n"
         "imgs = np.random.default_rng(1).integers(0, 255, (4, 64, 64, 3),\n"
         "                                         dtype=np.uint8)\n"
         "feats = ImageFeaturizer(input_col='image', output_col='features',\n"
         "                        model_name='ResNet18_External', image_size=64,\n"
         "                        repo_dir=os.path.join(tmpdir, 'zoo'))\n"
         "out = np.stack(feats.transform(DataFrame.from_dict({'image': imgs}))['features'])\n"
         "# parity with torch on the same preprocessed pixels\n"
         "with torch.no_grad():\n"
         "    ref = tm(torch.from_numpy(\n"
         "        np.asarray(normalize(imgs.astype(np.float32))).transpose(0, 3, 1, 2)))\n"
         "np.testing.assert_allclose(out, ref['pool'].numpy(), rtol=2e-2, atol=2e-2)\n"
         "print('torch feature parity:', out.shape)"),
    ],
    "DeepLearning - ViT with Sequence Parallelism.ipynb": [
        ("markdown",
         "# ViT featurization + sequence-parallel attention\n\n"
         "The zoo's transformer backbone: `ImageFeaturizer` serves ViT\n"
         "embeddings exactly like ResNet ones (same `cut_output_layers`\n"
         "semantics), and the encoder can shard its TOKEN dimension over\n"
         "the device mesh with ring attention — the long-context primitive\n"
         "(`ops/ring_attention`) inside a real model. Token counts that\n"
         "don't divide the mesh axis are padded and kv-masked."),
        ("code",
         "import numpy as np, tempfile\n"
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.models import ImageFeaturizer\n\n"
         "imgs = np.random.default_rng(0).integers(0, 255, (6, 32, 32, 3),\n"
         "                                         dtype=np.uint8)\n"
         "df = DataFrame.from_dict({'image': imgs})\n"
         "feat = ImageFeaturizer(input_col='image', output_col='features',\n"
         "                       model_name='ViTTiny', cut_output_layers=1,\n"
         "                       repo_dir=tempfile.mkdtemp())\n"
         "emb = np.stack(feat.transform(df)['features'])\n"
         "print('class-token embeddings:', emb.shape)"),
        ("markdown",
         "## Sequence parallelism\n\n"
         "The same weights, with the encoder's 65-token sequence ring-\n"
         "sharded over the mesh's `data` axis (padded to divide it). The\n"
         "outputs must match the dense single-device encoder."),
        ("code",
         "import jax, jax.numpy as jnp\n"
         "from mmlspark_tpu.models.vit import vit_tiny\n"
         "from mmlspark_tpu.parallel.mesh import get_mesh\n\n"
         "mesh = get_mesh()\n"
         "x = jnp.asarray(imgs[:2].astype(np.float32))\n"
         "dense = vit_tiny(num_classes=10, dtype=jnp.float32)\n"
         "ring = vit_tiny(num_classes=10, dtype=jnp.float32,\n"
         "                seq_mesh=mesh, seq_axis='data')\n"
         "vs = dense.init(jax.random.PRNGKey(0), x)\n"
         "pd = dense.apply(vs, x, train=False)['pool']\n"
         "pr = ring.apply(vs, x, train=False)['pool']\n"
         "print('mesh:', dict(mesh.shape),\n"
         "      'max |dense - ring|:', float(jnp.abs(pd - pr).max()))\n"
         "assert float(jnp.abs(pd - pr).max()) < 1e-3"),
        ("markdown",
         "External torchvision `vit_b_16` checkpoints install through\n"
         "`install_torch_checkpoint(..., variant='ViTB16')` with strict\n"
         "geometry validation — see the torch-import notebook."),
    ],
    "DeepLearning - BiLSTM Entity Extraction.ipynb": [
        ("markdown",
         "# BiLSTM entity extraction\n\n"
         "The recurrent member of the model zoo: a BiLSTM token tagger\n"
         "whose recurrence is a `lax.scan` under jit — one fixed-shape XLA\n"
         "program end to end — served batched through `XLAModel` exactly\n"
         "like the conv and transformer backbones. Padded batches carry\n"
         "`seq_lengths`; padding never leaks into real positions."),
        ("code",
         "import numpy as np\n"
         "from mmlspark_tpu.models.sequence import train_tagger\n\n"
         "# synthetic clinical-ish task: 'dosage' tokens (ids >= 40) are\n"
         "# tag 1; the token AFTER the trigger id 5 ('mg') is tag 2 —\n"
         "# tag 2 is only learnable with left context (the recurrence)\n"
         "rng = np.random.default_rng(0)\n"
         "tokens = rng.integers(1, 50, (64, 12))\n"
         "tags = np.where(tokens >= 40, 1, 0)\n"
         "trig = np.zeros_like(tokens); trig[:, 1:] = tokens[:, :-1] == 5\n"
         "tags = np.where(trig.astype(bool) & (tags == 0), 2, tags)\n"
         "lens = rng.integers(6, 13, (64,))\n"
         "model, vs = train_tagger(tokens, tags, vocab_size=50, num_tags=3,\n"
         "                         seq_lengths=lens, num_steps=150)"),
        ("code",
         "from mmlspark_tpu import DataFrame\n"
         "from mmlspark_tpu.models import XLAModel\n"
         "from mmlspark_tpu.models.sequence import pack_lengths\n\n"
         "# each row's true length rides as a trailing packed column, so\n"
         "# the pad mask holds on the serving path too\n"
         "xm = XLAModel(input_col='packed', output_col='tag_logits',\n"
         "              batch_size=16, input_dtype='int32')\n"
         "xm.set(apply_fn=model.packed_apply_fn(), variables=vs)\n"
         "df = DataFrame.from_dict({'packed': pack_lengths(tokens, lens)})\n"
         "out = np.stack(xm.transform(df)['tag_logits'])\n"
         "pred = out.argmax(-1)\n"
         "mask = np.arange(12)[None, :] < lens[:, None]\n"
         "acc = (pred == tags)[mask].mean()\n"
         "print('token tagging accuracy:', round(float(acc), 3))\n"
         "assert acc > 0.9"),
    ],
}


def main() -> None:
    os.makedirs(OUT, exist_ok=True)
    for name, cells in NOTEBOOKS.items():
        path = os.path.join(OUT, name)
        with open(path, "w") as f:
            json.dump(nb(cells), f, indent=1)
        print("wrote", path)


if __name__ == "__main__":
    main()
