"""Every fault-injection point wired into production code must be
exercised by at least one test.

The fault framework (core/faults.py) only proves anything when each
``faults.inject("<point>")`` call site has a chaos test arming a plan at
that point — an untested point is recovery machinery nobody has ever
watched recover. Same spirit as tools/lint_metric_names.py: grep-based,
wired into tier-1 (tests/test_tools.py), so a new injection point cannot
land without a test naming it.

- **Registered points**: string-literal first arguments of
  ``faults.inject(...)`` / ``inject(...)`` calls under the scan dirs
  (the production tree; tests and build outputs excluded).
- **Exercised**: the point's literal name appears in at least one file
  under ``tests/`` (a ``plan.on("point", ...)``, a JSON plan, or an
  assertion on its fires — any mention counts; the gate is grep-grade
  by design).

The same gate covers the WIRE-fault vocabulary: every rule kind in
``chaos/wire.py``'s ``RULE_KINDS`` tuple (latency, throttle, flip, ...)
must be named by at least one test — an untested wire fault is an
adversary nobody has ever watched the fleet survive.

A minimum-points guard protects the scan regex itself: if a refactor
moves injection sites out of the pattern's reach, the linter fails
loudly instead of silently passing an empty scan.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Iterator, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCAN_DIRS = ("mmlspark_tpu", "tools")
TEST_DIR = "tests"

# faults.inject("point", ...) with a literal first argument, possibly
# wrapped to the next line
_INJECT_RE = re.compile(
    r"""\b(?:faults\s*\.\s*)?inject\(\s*["']([a-z0-9_]+(?:\.[a-z0-9_]+)+)["']""",
    re.S,
)
# fewer registered points than this means the scan regex rotted, not
# that the tree lost its chaos hooks (PR 16 added the split-brain trio:
# registry.commit_cas — a registry refusing a generation CAS commit,
# elastic.park — a minority member stopping training on quorum loss,
# publish.fence — a worker rejecting a stale-epoch publication; the
# experiments subsystem added experiment.spawn — a trial charge failing
# to launch, experiment.report — a trial's rung report aborted before
# the wire, experiment.promote — a controller dying at the promotion
# decision; each is named by at least one test in test_elastic.py /
# test_online.py / test_experiments.py; PR 19's stall forensics added
# obs.watchdog_dump — a stall dump failing to spool, named in
# tests/test_stall_forensics.py; PR 20's shared-filesystem-free fleet
# added the placement/replication trio: artifact.push — one push
# attempt to a replica holder refused mid-transfer, artifact.replicate
# — a whole replication round denied before any byte moves,
# supervisor.spawn_remote — a remote scheduler refusing the
# allocation; each is named in tests/test_artifacts.py)
MIN_EXPECTED = 23

# chaos/wire.py's rule vocabulary: RULE_KINDS = ("latency", ...) —
# extracted by regex (same grep-grade spirit; an import would drag jax
# into a lint tool)
WIRE_RULES_FILE = os.path.join("mmlspark_tpu", "chaos", "wire.py")
_RULE_KINDS_RE = re.compile(r"RULE_KINDS\s*=\s*\(([^)]*)\)", re.S)
# fewer kinds than this means the extraction regex rotted
MIN_EXPECTED_KINDS = 4


def iter_sources(base_dirs: tuple = SCAN_DIRS) -> Iterator[str]:
    for d in base_dirs:
        for root, dirs, files in os.walk(os.path.join(REPO, d)):
            dirs[:] = [x for x in dirs if x != "__pycache__"]
            if f"{os.sep}build{os.sep}" in root + os.sep:
                continue
            for f in files:
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def registered_points(paths: Optional[list] = None) -> dict:
    """Point name -> first production file registering it."""
    points: dict = {}
    for path in paths or iter_sources():
        with open(path, encoding="utf-8") as f:
            src = f.read()
        rel = os.path.relpath(path, REPO)
        for m in _INJECT_RE.finditer(src):
            points.setdefault(m.group(1), rel)
    return points


def exercised_points(test_paths: Optional[list] = None) -> set:
    """Every dotted point name mentioned anywhere under tests/."""
    mentioned: set = set()
    paths = test_paths or [
        os.path.join(REPO, TEST_DIR, f)
        for f in os.listdir(os.path.join(REPO, TEST_DIR))
        if f.endswith(".py")
    ]
    name_re = re.compile(r"""["']([a-z0-9_]+(?:\.[a-z0-9_]+)+)["']""")
    for path in paths:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        mentioned.update(name_re.findall(src))
    return mentioned


def wire_rule_kinds(path: Optional[str] = None) -> list:
    """The RULE_KINDS tuple of chaos/wire.py, regex-extracted."""
    path = path or os.path.join(REPO, WIRE_RULES_FILE)
    try:
        with open(path, encoding="utf-8") as f:
            m = _RULE_KINDS_RE.search(f.read())
    except OSError:
        return []  # no chaos subsystem in this checkout: nothing to lint
    if m is None:
        return []
    return re.findall(r"""["']([a-z0-9_]+)["']""", m.group(1))


def lint_chaos_rules(
    test_paths: Optional[list] = None, rules_path: Optional[str] = None
) -> tuple:
    """Returns (untested_kinds, n_kinds): every wire-fault rule kind
    must appear verbatim in at least one test file."""
    kinds = wire_rule_kinds(rules_path)
    mentioned: set = set()
    paths = test_paths or [
        os.path.join(REPO, TEST_DIR, f)
        for f in os.listdir(os.path.join(REPO, TEST_DIR))
        if f.endswith(".py")
    ]
    word_re = re.compile(r"[a-z0-9_]+")
    for path in paths:
        with open(path, encoding="utf-8") as f:
            mentioned.update(word_re.findall(f.read()))
    return sorted(k for k in kinds if k not in mentioned), len(kinds)


def lint(
    paths: Optional[list] = None, test_paths: Optional[list] = None
) -> tuple:
    """Returns (violations, n_points); violations are (point, file)
    tuples for registered points no test ever names."""
    points = registered_points(paths)
    tested = exercised_points(test_paths)
    violations = sorted(
        (p, f) for p, f in points.items() if p not in tested
    )
    return violations, len(points)


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(prog="lint_fault_points.py")
    ap.add_argument("paths", nargs="*",
                    help="production files to scan (default: tree)")
    args = ap.parse_args(argv)
    violations, seen = lint(args.paths or None)
    if seen < MIN_EXPECTED and not args.paths:
        print(
            f"lint_fault_points: only {seen} injection points found "
            f"(expected >= {MIN_EXPECTED}) — the scan regex no longer "
            "matches the inject() idiom",
            file=sys.stderr,
        )
        return 2
    for point, rel in violations:
        print(
            f"{rel}: fault point {point!r} is exercised by no test "
            "(add a chaos test arming a FaultPlan at it)",
            file=sys.stderr,
        )
    # the chaos-rule check is repo-global (it greps ALL of tests/ for
    # every RULE_KINDS entry) — a path-scoped invocation must not fail
    # on state unrelated to the paths it was asked to lint
    untested_kinds, n_kinds = (
        ([], 0) if args.paths else lint_chaos_rules()
    )
    if n_kinds < MIN_EXPECTED_KINDS and not args.paths:
        print(
            f"lint_fault_points: only {n_kinds} wire rule kinds found "
            f"(expected >= {MIN_EXPECTED_KINDS}) — the RULE_KINDS "
            "extraction no longer matches chaos/wire.py",
            file=sys.stderr,
        )
        return 2
    for kind in untested_kinds:
        print(
            f"{WIRE_RULES_FILE}: wire rule kind {kind!r} is exercised by "
            "no test (add a ChaosProxy test applying it)",
            file=sys.stderr,
        )
    if violations or untested_kinds:
        print(
            f"lint_fault_points: {len(violations)} untested point(s) of "
            f"{seen}, {len(untested_kinds)} untested wire rule kind(s) "
            f"of {n_kinds}", file=sys.stderr,
        )
        return 1
    print(
        f"lint_fault_points: {seen} fault points and {n_kinds} wire rule "
        "kinds all exercised by tests"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
