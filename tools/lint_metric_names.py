"""Enforce the ``mmlspark_<subsystem>_<name>_<unit>`` metric naming
convention over the source tree.

Every metric registered through ``obs.counter/gauge/histogram`` with a
string-literal name is checked:

- prefix ``mmlspark_``;
- subsystem token from the known set (one per instrumented package —
  extend :data:`SUBSYSTEMS` when a new subsystem grows instruments);
- unit suffix from :data:`UNITS` (counters conventionally end ``_total``,
  including seconds-sum counters ``_seconds_total``);
- lowercase ``[a-z0-9_]`` only.

Run directly (``python tools/lint_metric_names.py``) or via the tier-1
test (tests/test_tools.py), so metric-name drift fails CI fast. A
minimum-hits sanity gate guards the regex itself: if a refactor moves
registrations out of the pattern's reach, the linter fails loudly rather
than silently passing an empty scan.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Iterator, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCAN_DIRS = ("mmlspark_tpu", "tools")

# "elastic" also covers the ring data plane's wire accounting
# (mmlspark_elastic_ring_steps_total, mmlspark_elastic_payload_bytes_total,
# overlap/vote counters — PR 14) and the split-brain fencing families
# (mmlspark_elastic_parks_total, mmlspark_elastic_fenced_writes_total,
# mmlspark_elastic_fenced_publications_total — PR 16); "registry" covers
# the generation CAS verdicts (mmlspark_registry_cas_commits_total) and
# "supervisor" the fenced-respawn deferrals
# (mmlspark_supervisor_fenced_respawns_total)
SUBSYSTEMS = (
    "core", "io", "serving", "gateway", "registry", "parallel", "gbdt",
    "faults", "trace", "modelstore", "slo", "admission", "supervisor",
    "compiler", "online", "autoscaler", "elastic", "artifact", "chaos",
    "experiments",
    # the replicated push plane (PR 20, serving/artifacts.py): pushes /
    # replicas / pull_resumes counters live under the plural "artifacts"
    # family prefix (the singular "artifact" covers the pull-side
    # fetch/verify instruments that predate it)
    "artifacts",
    # stall forensics (obs/prof.py, obs/watchdog.py, core/profiling.py):
    # sampling profiler, hang watchdog, compile/execute/host_callback
    # device-time attribution
    "prof", "watchdog", "device",
)
# "state" is for enum-valued gauges (e.g. the circuit-breaker gauge
# mmlspark_gateway_breaker_state: 0=closed 1=open 2=half-open)
UNITS = ("total", "seconds", "requests", "count", "bytes", "ratio", "rows",
         "state")

# registration call with a literal first argument, possibly wrapped to the
# next line: obs.counter(\n    "mmlspark_io_requests_total", ...
_REG_RE = re.compile(
    r"""\b(?:obs\s*\.\s*|REGISTRY\s*\.\s*|self\s*\.\s*)?"""
    r"""(counter|gauge|histogram)\(\s*["'](mmlspark_[a-zA-Z0-9_]*)["']""",
    re.S,
)
_NAME_RE = re.compile(
    r"^mmlspark_(%s)_[a-z0-9]+(_[a-z0-9]+)*_(%s)$"
    % ("|".join(SUBSYSTEMS), "|".join(UNITS))
)
# fewer hits than this means the scan regex rotted, not that the tree is
# clean — the instrumented subsystems register far more than this
MIN_EXPECTED = 15


def iter_sources() -> Iterator[str]:
    for d in SCAN_DIRS:
        for root, dirs, files in os.walk(os.path.join(REPO, d)):
            dirs[:] = [x for x in dirs if x != "__pycache__"]
            if f"{os.sep}build{os.sep}" in root + os.sep:
                continue
            for f in files:
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def lint(paths: Optional[list] = None) -> tuple:
    """Returns (violations, n_names_checked); violations are
    (path, name, why) tuples."""
    violations: list = []
    seen = 0
    for path in paths or iter_sources():
        with open(path, encoding="utf-8") as f:
            src = f.read()
        rel = os.path.relpath(path, REPO)
        for m in _REG_RE.finditer(src):
            name = m.group(2)
            seen += 1
            if _NAME_RE.match(name):
                continue
            if not re.match(r"^mmlspark_[a-z0-9_]+$", name):
                why = "name must be lowercase [a-z0-9_]"
            elif name.split("_")[1] not in SUBSYSTEMS:
                why = (
                    f"subsystem {name.split('_')[1]!r} not in "
                    f"{SUBSYSTEMS} (extend tools/lint_metric_names.py "
                    "when adding a subsystem)"
                )
            else:
                why = f"unit suffix must be one of {UNITS}"
            violations.append((rel, name, why))
    return violations, seen


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(prog="lint_metric_names.py")
    ap.add_argument("paths", nargs="*", help="files to lint (default: tree)")
    args = ap.parse_args(argv)
    violations, seen = lint(args.paths or None)
    if seen < MIN_EXPECTED and not args.paths:
        print(
            f"lint_metric_names: only {seen} metric registrations found "
            f"(expected >= {MIN_EXPECTED}) — the scan regex no longer "
            "matches the registration idiom",
            file=sys.stderr,
        )
        return 2
    for rel, name, why in violations:
        print(f"{rel}: {name}: {why}", file=sys.stderr)
    if violations:
        print(f"lint_metric_names: {len(violations)} violation(s) in "
              f"{seen} registrations", file=sys.stderr)
        return 1
    print(f"lint_metric_names: {seen} metric names ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
