"""Fleet smoke test: POST through the gateway, check replies + p50.

    python tools/deploy/smoke.py http://localhost:8080/ --n 50

Chaos smoke (``--fault-plan``): arm a deterministic fault plan
(mmlspark_tpu/core/faults.py) in THIS client and route every request
through the framework's retrying AdvancedHandler instead of a bare
socket. Injected wire faults (point ``io.send_request``: connection
errors, synthetic 5xx, latency) then hit the real retry/backoff path
against the real fleet, and the gate stays the same — 100% of requests
must complete. Example plan::

    {"seed": 0, "rules": [
      {"point": "io.send_request", "error": "ConnectionError",
       "probability": 0.2},
      {"point": "io.send_request", "payload": 503, "probability": 0.1}]}
"""

import argparse
import http.client
import json
import sys
import time
import urllib.parse


def _smoke_raw(u, n: int) -> tuple:
    conn = http.client.HTTPConnection(u.hostname, u.port or 80, timeout=10)
    lat = []
    ok = 0
    for i in range(n):
        body = json.dumps({"x": i})
        t0 = time.perf_counter()
        conn.request("POST", u.path or "/", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        lat.append((time.perf_counter() - t0) * 1e3)
        if resp.status == 200 and json.loads(data).get("echo", {}).get("x") == i:
            ok += 1
    conn.close()
    return ok, lat


def _smoke_chaos(url: str, n: int, fault_plan: str) -> tuple:
    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))))
    from mmlspark_tpu.core.faults import FaultPlan
    from mmlspark_tpu.io.clients import AdvancedHandler
    from mmlspark_tpu.io.http_schema import HTTPRequestData

    plan = FaultPlan.from_spec(fault_plan).install()
    handler = AdvancedHandler(backoffs_ms=(50, 200, 500, 1000), timeout=10.0)
    lat = []
    ok = 0
    for i in range(n):
        t0 = time.perf_counter()
        resp = handler(HTTPRequestData(
            url, "POST", {"Content-Type": "application/json"},
            json.dumps({"x": i}),
        ))
        lat.append((time.perf_counter() - t0) * 1e3)
        if (
            resp["status_code"] == 200
            and json.loads(resp["entity"]).get("echo", {}).get("x") == i
        ):
            ok += 1
    print(f"smoke: {len(plan.fires())} faults injected")
    return ok, lat


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="smoke.py", description=__doc__)
    ap.add_argument("url", nargs="?", default="http://127.0.0.1:8080/")
    ap.add_argument("n_requests", nargs="?", type=int, default=None,
                    help="positional alias for --n (back-compat)")
    ap.add_argument("--n", type=int, default=50)
    ap.add_argument(
        "--fault-plan", default=None,
        help="JSON fault plan (inline or file path): chaos-smoke through "
        "the retrying client instead of a bare socket",
    )
    args = ap.parse_args(argv)
    n = args.n_requests if args.n_requests is not None else args.n
    if args.fault_plan:
        ok, lat = _smoke_chaos(args.url, n, args.fault_plan)
    else:
        ok, lat = _smoke_raw(urllib.parse.urlparse(args.url), n)
    lat.sort()
    p50 = lat[len(lat) // 2]
    print(f"smoke: {ok}/{n} ok, p50 {p50:.2f} ms")
    return 0 if ok == n else 1


if __name__ == "__main__":
    raise SystemExit(main())
