"""Fleet smoke test: POST through the gateway, check replies + p50.

    python tools/deploy/smoke.py http://localhost:8080/ --n 50

Metrics verification (on by default; ``--no-verify-metrics`` to skip):
the gateway and workers are scraped via ``GET /metrics`` before and
after the request phase, and the delta of
``mmlspark_gateway_requests_total`` (requests forwarded AND answered)
must equal the client-observed successes — a live-fleet gate for silent
drops that the chaos suite's in-process assertions can't see. With
``--registry`` the per-worker accepted counters are summed and checked
too. Under ``--fault-plan`` the client retries, so the gate relaxes to
``forwarded >= successes``.

Hot-swap drill (``--swap``): while the request phase runs, load a new
version of ``--swap-model`` (spec ``--swap-spec``) on every backend and
swap it in — with ``--registry`` each rostered worker's control plane is
driven directly, otherwise the load/swap POSTs ride the target URL. The
gate then requires the forwarded-counter delta to equal client successes
ACROSS the flip (the two control ops per backend are accounted for), so
a swap that drops even one request fails the smoke.

Containment gate (default on, with the metrics gate): after the run the
gateway's breaker gauges must be sane (every
``mmlspark_gateway_breaker_state`` in {closed, open, half-open}, retry
budget in [0, 1]); when ``gateway.forward`` faults were injected in the
gateway's own process (a fleet role armed with ``--fault-plan``), a
breaker must additionally have OPENED at least once — proof the
containment layer reacts to chaos rather than sleeping through it.

Freshness gate (default on): when a continuous-learning loop is
rostered under ``<service>-online`` (or the target itself exports
``mmlspark_online_*`` metrics), its freshness histogram must have
recorded a publication and its freshness SLO burn must not be red —
fleets without online learning skip (docs/online-learning.md).

Chaos smoke (``--fault-plan``): arm a deterministic fault plan
(mmlspark_tpu/core/faults.py) in THIS client and route every request
through the framework's retrying AdvancedHandler instead of a bare
socket. Injected wire faults (point ``io.send_request``: connection
errors, synthetic 5xx, latency) then hit the real retry/backoff path
against the real fleet, and the gate stays the same — 100% of requests
must complete. Example plan::

    {"seed": 0, "rules": [
      {"point": "io.send_request", "error": "ConnectionError",
       "probability": 0.2},
      {"point": "io.send_request", "payload": 503, "probability": 0.1}]}
"""

import argparse
import http.client
import json
import os
import sys
import time
import urllib.parse


def _ensure_repo_path() -> None:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if root not in sys.path:
        sys.path.insert(0, root)


def _smoke_raw(u, n: int) -> tuple:
    conn = http.client.HTTPConnection(u.hostname, u.port or 80, timeout=10)
    lat = []
    ok = 0
    for i in range(n):
        body = json.dumps({"x": i})
        t0 = time.perf_counter()
        conn.request("POST", u.path or "/", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        lat.append((time.perf_counter() - t0) * 1e3)
        if resp.status == 200 and json.loads(data).get("echo", {}).get("x") == i:
            ok += 1
    conn.close()
    return ok, lat


def _fleet_counters(gateway_url: str, registry_url, service: str) -> dict:
    """The accepted/forwarded counters the drop-gate compares.

    ``gateway_forwarded`` is None when the target exposes no gateway
    metrics (pre-telemetry build, or smoking a worker directly) — the
    gate then skips rather than failing a healthy fleet."""
    _ensure_repo_path()
    from mmlspark_tpu import obs
    from mmlspark_tpu.serving.fleet import (
        scrape_metrics, worker_urls_from_registry,
    )

    gw = scrape_metrics(gateway_url)
    # "is the target actually a gateway?" — the gateway family registers
    # at import time in EVERY serving process (package __init__ pulls in
    # distributed.py), so family presence proves nothing; a constructed
    # ServingGateway is detected by its ingress server label
    # ("<service>-gateway", pre-bound at construction)
    has_gw = gw is not None and any(
        name == "mmlspark_serving_requests_total"
        and any(k == "server" and v.endswith("-gateway") for k, v in labels)
        for name, labels in gw
    )
    out = {
        "gateway_forwarded": (
            obs.sum_samples(gw, "mmlspark_gateway_requests_total")
            if has_gw else None
        ),
        "workers_accepted": None,
        # raw gateway scrape for the containment gate (breaker/budget
        # deltas need more than one pre-summed counter)
        "gateway_raw": gw if has_gw else None,
    }
    if registry_url:
        try:
            urls = worker_urls_from_registry(registry_url, service)
        except Exception as e:  # noqa: BLE001 — gate degrades, smoke goes on
            print(f"smoke: registry scrape failed ({e}); "
                  "skipping worker-counter gate")
            urls = None
        if urls is not None:
            total = 0.0
            for wurl in urls:
                total += obs.sum_samples(
                    scrape_metrics(wurl) or {},
                    "mmlspark_serving_requests_total", {"server": service},
                )
            out["workers_accepted"] = total
    return out


def _verify_metrics(before: dict, after: dict, ok: int, chaos: bool,
                    extra_gw: int = 0, extra_workers: int = 0) -> bool:
    """Gate: forwarded-request delta must account for every client-observed
    success (equality without faults; >= under client-side fault
    injection, where retries resend the same logical request).
    ``extra_gw`` / ``extra_workers``: control-plane requests the drill
    itself sent through the gateway / to the workers (the --swap load+swap
    POSTs), which the counters legitimately include."""
    good = True
    if after.get("gateway_forwarded") is None or (
        before.get("gateway_forwarded") is None
    ):
        print("smoke: target exposes no gateway metrics; "
              "skipping forwarded-counter gate")
    else:
        fwd = after["gateway_forwarded"] - before["gateway_forwarded"]
        want = ok + extra_gw
        good = fwd >= want if chaos else fwd == want
        print(f"smoke: gateway forwarded delta {fwd:.0f} vs {want} client "
              f"successes{' + control ops' if extra_gw else ''} — "
              f"{'ok' if good else 'MISMATCH'}")
    if after.get("workers_accepted") is not None and (
        before.get("workers_accepted") is not None
    ):
        wacc = after["workers_accepted"] - before["workers_accepted"]
        want = ok + extra_workers
        w_good = wacc >= want if chaos else wacc == want
        print(f"smoke: workers accepted delta {wacc:.0f} vs {want} client "
              f"successes{' + control ops' if extra_workers else ''} — "
              f"{'ok' if w_good else 'MISMATCH'}")
        good = good and w_good
    return good


def _swap_drill(url: str, n: int, registry_url, service: str,
                model: str, spec: str) -> tuple:
    """Hot-swap drill: sustain ``n`` requests while every backend loads a
    new version of ``model`` and swaps it in mid-traffic. With
    ``--registry`` the control plane is driven on each rostered worker
    directly; otherwise the load/swap POSTs ride the target URL (single-
    backend fleets, or a worker smoked directly — through a gateway the
    two control ops also count as forwarded requests, which the metrics
    gate accounts for).

    Returns (ok, latencies_ms, swap_ok, extra_gw, extra_workers)."""
    import threading

    _ensure_repo_path()
    from mmlspark_tpu.io.clients import send_request
    from mmlspark_tpu.io.http_schema import HTTPRequestData

    result: dict = {}

    def traffic() -> None:
        try:
            result["ok"], result["lat"] = _smoke_raw(
                urllib.parse.urlparse(url), n
            )
        except Exception as e:  # noqa: BLE001 — report, don't KeyError later
            result["error"] = f"{type(e).__name__}: {e}"
            result.setdefault("ok", 0)
            result.setdefault("lat", [float("nan")])

    t = threading.Thread(target=traffic)
    t.start()
    # the flip must land mid-traffic, not after a short run already ended
    time.sleep(0.3 if n >= 200 else 0.05)
    targets = None
    if registry_url:
        from mmlspark_tpu.serving.fleet import worker_urls_from_registry

        try:
            targets = worker_urls_from_registry(registry_url, service)
        except Exception as e:  # noqa: BLE001 — degrade to the target URL
            print(f"smoke: registry unavailable ({e}); swapping via {url}")
    via_gateway = not targets
    if via_gateway:
        targets = [url]
    swapped = 0
    for base in targets:
        base = base.rstrip("/")
        if via_gateway:
            # one control op: load-and-activate atomically on whichever
            # backend the gateway picks. Two separate load+swap POSTs
            # would round-robin onto DIFFERENT replicas in a multi-worker
            # fleet (no stickiness) and the swap would find nothing to
            # flip — use --registry to drill every replica's explicit
            # swap verb instead
            loaded = send_request(HTTPRequestData(
                f"{base}/models/{model}/load", "POST",
                {"Content-Type": "application/json"},
                json.dumps({"spec": spec, "activate": "always"}),
            ), timeout=300.0)
            ok_flip = loaded["status_code"] in (200, 202)
            if not ok_flip:
                print(f"smoke: swap via {base} failed: load "
                      f"{loaded['status_code']} {loaded['entity'][:200]}")
            print("smoke: no registry — load+activate drilled ONE backend "
                  "through the gateway (pass --registry to flip them all)")
        else:
            loaded = send_request(HTTPRequestData(
                f"{base}/models/{model}/load", "POST",
                {"Content-Type": "application/json"},
                json.dumps({"spec": spec}),
            ), timeout=300.0)
            flipped = send_request(HTTPRequestData(
                f"{base}/models/{model}/swap", "POST",
                {"Content-Type": "application/json"}, "{}",
            ), timeout=300.0)
            ok_flip = (
                loaded["status_code"] in (200, 202)
                and flipped["status_code"] == 200
            )
            if not ok_flip:
                print(f"smoke: swap on {base} failed: load "
                      f"{loaded['status_code']} swap "
                      f"{flipped['status_code']} {flipped['entity'][:200]}")
        if ok_flip:
            swapped += 1
    t.join()
    if "error" in result:
        print(f"smoke: traffic phase died mid-drill: {result['error']}")
    print(f"smoke: swap drill — {swapped}/{len(targets)} backend(s) "
          "flipped mid-traffic")
    # control ops also land in the counters: via the gateway the single
    # load POST was forwarded (and accepted by one worker); driven
    # directly, the 2 POSTs per worker touched only the accepted counters
    extra_gw = 1 * len(targets) if via_gateway else 0
    extra_workers = (1 if via_gateway else 2) * len(targets)
    return (
        result["ok"], result["lat"], swapped == len(targets),
        extra_gw, extra_workers,
    )


def _verify_containment(before: dict, after: dict, plan=None) -> bool:
    """Containment gate (default on): the gateway's failure-containment
    surfaces must be present and sane after the run — every
    ``mmlspark_gateway_breaker_state`` gauge in {closed, open,
    half-open}, the retry-budget gauge in [0, 1] — and when the fault
    plan guarantees a breaker-tripping burst (a contiguous always-fire
    ``gateway.forward`` error rule, with enough fires *in the gateway
    process* for >= 3 consecutive failures per backend — the default
    consecutive-failure threshold), a breaker must actually have OPENED
    at least once: chaos that the containment layer slept through is a
    failed gate, not a quiet pass. Scattered schedules (probability
    draws, ``every``-N strides, sparse ``at`` lists) interleave
    successes that reset the failure streak — chaos the breaker is
    *right* not to trip on, so the opened requirement is waived. Skips
    on targets without breaker gauges (pre-containment build, or a
    worker smoked directly)."""
    _ensure_repo_path()
    from mmlspark_tpu import obs

    gw_b, gw_a = before.get("gateway_raw"), after.get("gateway_raw")
    if gw_a is None:
        print("smoke: target exposes no gateway metrics; "
              "skipping containment gate")
        return True
    states = {
        dict(labels).get("backend", "?"): v
        for (name, labels), v in gw_a.items()
        if name == "mmlspark_gateway_breaker_state"
    }
    if not states:
        print("smoke: gateway exports no breaker gauges; "
              "skipping containment gate")
        return True
    good = all(v in (0.0, 1.0, 2.0) for v in states.values())
    budget = [
        v for (name, _labels), v in gw_a.items()
        if name == "mmlspark_gateway_retry_budget_remaining_ratio"
    ]
    budget_ok = bool(budget) and all(0.0 <= v <= 1.0 for v in budget)
    n_open = sum(1 for v in states.values() if v != 0.0)
    budget_str = (
        f"retry budget {budget[0] * 100:.0f}%" if budget
        else "retry budget gauge MISSING"
    )
    print(
        f"smoke: containment — {len(states)} breaker(s), {n_open} not "
        f"closed, {budget_str}"
    )
    good = good and budget_ok

    def delta(name, match=None):
        a = obs.sum_samples(gw_a, name, match)
        b = obs.sum_samples(gw_b, name, match) if gw_b is not None else 0.0
        return a - b

    injected_fw = delta(
        "mmlspark_faults_injected_total", {"point": "gateway.forward"}
    )
    # a contiguous always-fire error rule means EVERY forward failed while
    # it was live: round-robined across the pool, each backend's streak
    # grows uninterrupted, so >= 3 fires per breaker guarantees a trip.
    # An `at` list counts when it contains a run of >= 3 consecutive steps
    def _longest_run(at) -> int:
        s = sorted(at)
        best = run = 1 if s else 0
        for a, b in zip(s, s[1:]):
            run = run + 1 if b == a + 1 else 1
            best = max(best, run)
        return best

    burst = plan is not None and any(
        r.error is not None and r.probability >= 1.0 and r.every <= 1
        and (r.at is None or _longest_run(r.at) >= 3)
        for r in plan.rules("gateway.forward")
    )
    # fires-per-backend denominator: the pool's live-backend gauge (the
    # breaker-gauge count includes stale series from departed backends —
    # and, in-process, from other gateway instances sharing the registry)
    pool_size = next(
        (v for (name, _l), v in gw_a.items()
         if name == "mmlspark_gateway_backends_count"), 0.0,
    )
    per_backend = int(pool_size) if pool_size >= 1 else len(states)
    if burst and injected_fw >= 3 * max(1, per_backend):
        opened = delta(
            "mmlspark_gateway_breaker_transitions_total", {"state": "open"}
        )
        opened_ok = opened >= 1
        verdict = "ok" if opened_ok else "MISMATCH (chaos never tripped one)"
        print(
            f"smoke: {injected_fw:.0f} gateway.forward fault(s) hit the "
            f"gateway, breaker opened {opened:.0f} time(s) — {verdict}"
        )
        good = good and opened_ok
    elif injected_fw:
        print(
            f"smoke: {injected_fw:.0f} gateway.forward fault(s) hit the "
            f"gateway (schedule not guaranteed to trip a breaker — "
            f"open requirement waived)"
        )
    return good


def _verify_trace(url: str, registry_url, service: str) -> bool:
    """Trace-assembly gate (default on): fetch the slowest trace via the
    collector and require both a gateway hop and a worker hop in the
    assembled tree. Degrades rather than failing a healthy fleet (the
    PR 2 metrics-gate precedent): skips when nothing serves ``/traces``
    (pre-trace build) or when the target buffers no gateway spans
    (smoking a worker directly), and only requires the worker hop when
    worker span buffers were actually scraped (``--registry``) or the
    target's own buffer already holds worker spans (co-located roles)."""
    _ensure_repo_path()
    from mmlspark_tpu.obs import traces as traces_mod
    from mmlspark_tpu.serving.fleet import worker_urls_from_registry

    target = url.rstrip("/")
    endpoints = [target]
    if registry_url:
        try:
            endpoints += [
                u for u in worker_urls_from_registry(registry_url, service)
                if u not in endpoints
            ]
        except Exception as e:  # noqa: BLE001 — gate degrades, smoke goes on
            print(f"smoke: registry unavailable for trace gate ({e})")
    spans, exemplars, scraped = traces_mod.collect(endpoints)
    if not scraped:
        print("smoke: no endpoint serves /traces; skipping trace gate")
        return True
    if not any(s.name == "gateway.request" for s in spans):
        # a worker smoked directly has no gateway spans to assemble
        print("smoke: target buffers no gateway traces; skipping trace gate")
        return True
    # slowest exemplar first — but exemplars outlive the bounded span
    # rings (a bucket remembers its LAST observation's trace id forever,
    # the ring ages out), so fall back through the ranking to the first
    # exemplar that still resolves to buffered spans, then to the latest
    # gateway-rooted trace. A long-lived fleet must not fail the gate on
    # a stale exemplar.
    tid, tspans, how = None, [], ""
    for v, cand in traces_mod.slowest_traces(exemplars, n=5):
        cand_spans = [s for s in spans if s.trace_id == cand]
        if cand_spans:
            tid, tspans = cand, cand_spans
            how = f"slowest live exemplar trace {cand} ({v * 1e3:.2f} ms)"
            break
    if tid is None:
        # cold (or fully aged-out) exemplars: any gateway-rooted trace
        gw_spans = [s for s in spans if s.name == "gateway.request"]
        tid = gw_spans[-1].trace_id
        tspans = [s for s in spans if s.trace_id == tid]
        how = f"latest gateway trace {tid}"
    # worker spans are only observable when worker buffers were scraped
    # (or the target process co-hosts the worker); without --registry a
    # gateway-only smoke must not fail on spans it cannot see
    workers_scraped = len(scraped) > 1
    worker_seen = any(
        s.name in ("serving.request", "serving.dispatch", "serving.queue",
                   "modelstore.dispatch")
        for s in spans
    )
    require_worker = workers_scraped or worker_seen
    ok = traces_mod.has_gateway_and_worker_hop(tspans) if require_worker \
        else any(s.name.startswith("gateway.") for s in tspans)
    hops = "gateway+worker" if require_worker else \
        "gateway-only (pass --registry to scrape worker buffers)"
    print(
        f"smoke: {how} — {len(tspans)} span(s) across "
        f"{len({s.process for s in tspans})} process(es), {hops} "
        f"hops {'ok' if ok else 'MISMATCH'}"
    )
    if not ok:
        print(traces_mod.render_tree(tspans, tid))
    return ok


def _verify_profile(url: str, registry_url, service: str,
                    overhead_bound: float = 0.05) -> bool:
    """Stall-forensics gate (default on; ``--no-verify-profile`` to
    skip): ``GET /profile`` must answer on the target (gateway) and —
    with ``--registry`` — on at least one rostered worker; the scrape
    itself starts a sampler that wasn't running, and the sampler's
    overhead gauge must stay under ``overhead_bound`` of one core.
    Degrades on pre-profiler builds (404 -> skip, the PR 2 precedent)."""
    _ensure_repo_path()
    from mmlspark_tpu import obs
    from mmlspark_tpu.obs import prof
    from mmlspark_tpu.serving.fleet import (
        scrape_metrics,
        scrape_profile,
        worker_urls_from_registry,
    )

    targets = [("target", url.rstrip("/"))]
    if registry_url:
        try:
            workers = worker_urls_from_registry(registry_url, service)
            if workers:
                targets.append(("worker", workers[0]))
        except Exception as e:  # noqa: BLE001 — gate degrades, smoke goes on
            print(f"smoke: registry unavailable for profile gate ({e})")
    ok = True
    answered = 0
    for role, base in targets:
        text = scrape_profile(base)
        if text is None:
            print(f"smoke: {role} {base} does not serve /profile; skipping")
            continue
        answered += 1
        # the first scrape may have just started the sampler: give it a
        # beat so the second read sees samples + a live overhead gauge
        time.sleep(0.3)
        text = scrape_profile(base) or text
        stacks = prof.parse_collapsed(text)
        running = "# running: true" in text
        print(
            f"smoke: {role} /profile ok ({len(stacks)} stack(s), "
            f"sampler {'running' if running else 'stopped'})"
        )
        parsed = scrape_metrics(base)
        if parsed is not None:
            oh = obs.sum_samples(parsed, "mmlspark_prof_overhead_ratio")
            good = oh < overhead_bound
            print(
                f"smoke: {role} sampler overhead {oh:.4f} "
                f"{'ok' if good else f'MISMATCH (>= {overhead_bound})'}"
            )
            ok = good and ok
    if not answered:
        print(
            "smoke: no endpoint serves /profile (pre-profiler build); "
            "skipping profile gate"
        )
    return ok


def _verify_slo(url: str) -> bool:
    """SLO gate: when the target exports ``mmlspark_slo_*`` gauges, fail
    on a red (page-now) target; skip on fleets without the engine."""
    _ensure_repo_path()
    from mmlspark_tpu.obs import slo as slo_mod
    from mmlspark_tpu.serving.fleet import scrape_metrics

    parsed = scrape_metrics(url)
    if parsed is None:
        print("smoke: target /metrics unreachable; skipping SLO gate")
        return True
    status = slo_mod.status_from_scrape(parsed)
    if status is None:
        print("smoke: target exports no SLO gauges; skipping SLO gate")
        return True
    burns = sorted(
        (dict(labels).get("slo", "?"), dict(labels).get("window", "?"), v)
        for (name, labels), v in parsed.items()
        if name == "mmlspark_slo_burn_rate_ratio"
    )
    for slo_name, window, v in burns:
        print(f"smoke: slo {slo_name} burn[{window}] = {v:.3f}")
    ok = status < slo_mod.RED
    print(
        f"smoke: slo status {slo_mod.STATUS_NAMES.get(status, '?')} — "
        f"{'ok' if ok else 'RED (error budget burning at page rate)'}"
    )
    return ok


def _freshness_ok(parsed: dict, where: str) -> bool:
    """One online endpoint's freshness verdict (pure — unit-testable):
    the freshness histogram must have recorded at least one publication
    and the freshness SLO status (any ``mmlspark_slo_status_count``
    whose target name contains 'freshness') must not be red. A loop
    that has not ATTEMPTED a publication yet skips rather than failing
    — idle (nothing ingested) or just-started (first publish interval
    not elapsed) are both healthy; attempted-but-never-succeeded is
    the real failure (the loop exists and cannot make models
    servable — the failure counter and a red burn carry the evidence).
    """
    _ensure_repo_path()
    from mmlspark_tpu import obs
    from mmlspark_tpu.obs import slo as slo_mod

    ingested = obs.sum_samples(parsed, "mmlspark_online_ingested_total")
    attempts = obs.sum_samples(
        parsed, "mmlspark_online_publish_attempts_total"
    )
    published = obs.sum_samples(
        parsed, "mmlspark_online_freshness_seconds_count"
    )
    if attempts == 0:
        why = (
            "idle (nothing ingested)" if ingested == 0
            else "no publication due yet"
        )
        print(f"smoke: online loop at {where} is {why}; "
              "freshness gate skipped for it")
        return True
    status = None
    for (name, labels), v in parsed.items():
        if name == "mmlspark_slo_status_count" and (
            "freshness" in dict(labels).get("slo", "")
        ):
            status = max(status or 0, int(v))
    present = published >= 1
    non_red = status is None or status < slo_mod.RED
    verdict = (
        "ok" if present and non_red
        else "MISMATCH (no publication recorded)" if not present
        else "MISMATCH (freshness burn is RED)"
    )
    status_str = (
        slo_mod.STATUS_NAMES.get(status, "?") if status is not None
        else "no-slo-gauge"
    )
    print(
        f"smoke: freshness at {where} — {published:.0f} publication(s) "
        f"measured, slo {status_str} — {verdict}"
    )
    return present and non_red


def _verify_freshness(url: str, registry_url, service: str) -> bool:
    """Freshness gate (default on): when a continuous-learning loop is
    rostered under ``<service>-online`` (or the smoke target itself
    exports ``mmlspark_online_*`` metrics), its freshness histogram
    must be present and its freshness SLO non-red; fleets without an
    online loop skip — the gate never fails a deployment for not doing
    continuous learning (docs/online-learning.md)."""
    _ensure_repo_path()
    from mmlspark_tpu.serving.fleet import (
        scrape_metrics, worker_urls_from_registry,
    )

    candidates: list = []
    if registry_url:
        try:
            for u in worker_urls_from_registry(
                registry_url, f"{service}-online"
            ):
                candidates.append(u)
        except Exception as e:  # noqa: BLE001 — gate degrades, smoke goes on
            print(f"smoke: registry unavailable for freshness gate ({e})")
    target = scrape_metrics(url)
    parsed_by_url = {u: scrape_metrics(u) for u in candidates}
    if target is not None and any(
        name == "mmlspark_online_publish_attempts_total"
        for (name, _labels) in target
    ) and url not in parsed_by_url:
        parsed_by_url[url] = target  # co-located loop (in-process fleets)
    live = {u: p for u, p in parsed_by_url.items() if p is not None}
    if not live:
        print("smoke: no online loop rostered; skipping freshness gate")
        return True
    return all(_freshness_ok(p, u) for u, p in live.items())


# ~2000 json dumps of the calibration payload on the reference box
# (24-core dev machine)
_REF_SPIN_S = 0.0065


def box_speed_factor(max_factor: float = 8.0) -> float:
    """How much slower this box is than the reference box, as a >= 1.0
    multiplier for wall-clock budgets. The probe is the same JSON-encode
    spin the throughput floor calibrates against, so the two gates
    agree on what "slow" means. Load-sensitive chaos drills scale their
    TIMING budgets by this factor instead of demoting their zero-drop
    contracts to slow-only runs — a loaded CI box gets more seconds,
    never a weaker gate. Capped (default 8x) so a wedged box still
    fails instead of waiting forever."""
    payload = {"x": list(range(16)), "k": "calibration"}
    t0 = time.perf_counter()
    for _ in range(2000):
        json.dumps(payload)
    spin_s = max(time.perf_counter() - t0, 1e-6)
    return min(max(1.0, spin_s / _REF_SPIN_S), max_factor)


def _throughput_floor_rps(base_floor: float = 50.0) -> float:
    """Box-speed-scaled rps floor: the reference box clears ~500+ rps
    through the gateway, so a 50-rps floor is ~10x margin there; a
    slower box scales the floor down by its measured JSON-encode speed
    (the inverse of :func:`box_speed_factor`) rather than flaking the
    gate."""
    return max(5.0, base_floor / box_speed_factor(max_factor=10.0))


def _verify_throughput(url: str, n: int = 120, threads: int = 4) -> bool:
    """Throughput sanity gate (default on): ``n`` keep-alive requests
    from ``threads`` concurrent pipelined clients through the gateway,
    with a floor on achieved rps scaled to box speed — a data-plane
    regression (lost keep-alive, serialized dispatch, a stalled
    reactor) fails smoke instead of waiting for the next bench run.
    Skips when the target isn't a gateway (worker-direct smokes measure
    the model, not the data plane). Runs AFTER the counter-gate
    scrapes, so its traffic never skews the forwarded==successes
    equality."""
    _ensure_repo_path()
    import http.client
    import threading as threading_mod

    from mmlspark_tpu.serving.fleet import scrape_metrics

    parsed = scrape_metrics(url)
    has_gw = parsed is not None and any(
        name == "mmlspark_serving_requests_total"
        and any(k == "server" and v.endswith("-gateway") for k, v in labels)
        for (name, labels) in parsed
    )
    if not has_gw:
        print("smoke: target exposes no gateway metrics; "
              "skipping throughput gate")
        return True
    u = urllib.parse.urlparse(url)
    port = u.port or 80
    per_thread = max(1, n // threads)
    lock = threading_mod.Lock()
    counts = {"done": 0, "err": 0, "fail5xx": 0}

    def client(k: int) -> None:
        conn = http.client.HTTPConnection(u.hostname, port, timeout=15)
        for i in range(per_thread):
            try:
                conn.request(
                    "POST", "/", body=json.dumps({"x": i}),
                    headers={"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                resp.read()
                with lock:
                    counts["done"] += 1
                    if resp.status >= 500:
                        counts["fail5xx"] += 1
            except Exception:  # noqa: BLE001 — transport error = gate evidence
                with lock:
                    counts["err"] += 1
                conn.close()
                conn = http.client.HTTPConnection(u.hostname, port, timeout=15)
        conn.close()

    floor = _throughput_floor_rps()
    t0 = time.perf_counter()
    ts = [threading_mod.Thread(target=client, args=(k,))
          for k in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(120.0)
    elapsed = max(time.perf_counter() - t0, 1e-6)
    total = threads * per_thread
    rps = counts["done"] / elapsed
    ok = (
        counts["done"] == total
        and counts["err"] == 0
        and counts["fail5xx"] <= total * 0.1
        and rps >= floor
    )
    print(
        f"smoke: throughput — {counts['done']}/{total} replies from "
        f"{threads} pipelined clients in {elapsed:.2f}s = {rps:.0f} rps "
        f"(floor {floor:.0f}), {counts['err']} transport error(s), "
        f"{counts['fail5xx']} 5xx — {'ok' if ok else 'MISMATCH'}"
    )
    return ok


def _count_fault_records() -> int:
    _ensure_repo_path()
    from mmlspark_tpu.obs.flightrec import FLIGHT

    return len(FLIGHT.snapshot(outcome="fault"))


def _verify_flightrec(plan, recorded_before: int) -> bool:
    """Chaos-smoke gate: every injected fault must appear in this
    process's flight recorder (faults.inject records one event per
    fire), so a dump explains exactly what chaos did. Compared as a
    delta: an in-process caller may hold records from earlier runs."""
    injected = len(plan.fires())
    recorded = _count_fault_records() - recorded_before
    ok = recorded == injected
    print(
        f"smoke: flight recorder captured {recorded}/{injected} injected "
        f"fault(s) — {'ok' if ok else 'MISMATCH'}"
    )
    return ok


def _smoke_chaos(url: str, n: int, fault_plan: str) -> tuple:
    _ensure_repo_path()
    from mmlspark_tpu.core.faults import FaultPlan
    from mmlspark_tpu.io.clients import AdvancedHandler
    from mmlspark_tpu.io.http_schema import HTTPRequestData

    plan = FaultPlan.from_spec(fault_plan).install()
    handler = AdvancedHandler(backoffs_ms=(50, 200, 500, 1000), timeout=10.0)
    lat = []
    ok = 0
    for i in range(n):
        t0 = time.perf_counter()
        resp = handler(HTTPRequestData(
            url, "POST", {"Content-Type": "application/json"},
            json.dumps({"x": i}),
        ))
        lat.append((time.perf_counter() - t0) * 1e3)
        if (
            resp["status_code"] == 200
            and json.loads(resp["entity"]).get("echo", {}).get("x") == i
        ):
            ok += 1
    print(f"smoke: {len(plan.fires())} faults injected")
    return ok, lat, plan


def _verify_chaos_wire(
    url: str, registry_url, service: str, seed: int = 7, n: int = 40,
    partition: bool = False,
) -> bool:
    """Opt-in hostile-wire gate (``--chaos-wire``): run a short SEEDED
    wire-fault schedule — latency+jitter, a bandwidth throttle, and a
    slowloris connection — through a ChaosProxy fronting the gateway,
    then require (a) the normal traffic still completed, (b) the
    slowloris was shed without wedging anything, and (c) the fleet-wide
    invariant checker comes back green: chaos may cost latency or shed
    requests, never accounting (docs/chaos.md). With ``partition``
    (``--chaos-wire-partition``) the gate also runs a conductor-driven
    partition/heal probe: a blackholed link must pass NOTHING, a healed
    one must serve again — the same actions the split-brain drills use
    (docs/chaos.md), proved against the live fleet."""
    _ensure_repo_path()
    import socket as socket_mod

    from mmlspark_tpu.chaos.invariants import InvariantChecker
    from mmlspark_tpu.chaos.wire import ChaosProxy, WireRule

    u = urllib.parse.urlparse(url)
    proxy = ChaosProxy(
        u.hostname, u.port or 80, seed=seed, name="smoke-gw",
        rules=[
            WireRule("latency", delay_ms=2.0, jitter_ms=5.0),
            WireRule("throttle", direction="c2s", bytes_per_s=256 * 1024),
        ],
    ).start()
    try:
        ok = 0
        for i in range(n):
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", proxy.port, timeout=15.0
                )
                conn.request(
                    "POST", u.path or "/", json.dumps({"x": i}),
                    {"Content-Type": "application/json"},
                )
                if conn.getresponse().status == 200:
                    ok += 1
                conn.close()
            except OSError:
                pass
        # one slowloris: while a client drips a torn head and never
        # finishes it, OTHER connections must keep being served — the
        # non-stalling property (the 408 shed itself lands at the
        # ingress's header deadline, too long to wait out in a smoke)
        shed = True
        dripper = None
        try:
            dripper = socket_mod.create_connection(
                (u.hostname, u.port or 80), timeout=2.0
            )
            dripper.sendall(b"GET /heal")  # torn head, never completed
        except OSError as e:
            # no dripper on the wire = the non-stalling property was
            # NOT tested — that must fail the gate, never pass it
            # vacuously (the dripper dials the gateway direct, off the
            # chaos link, so a refused connect is a real problem)
            print(f"smoke: chaos-wire slowloris dripper failed to "
                  f"connect: {e}")
            shed = False
        for i in range(3):
            try:
                conn = http.client.HTTPConnection(
                    u.hostname, u.port or 80, timeout=5.0
                )
                conn.request(
                    "POST", u.path or "/", json.dumps({"drip": i}),
                    {"Content-Type": "application/json"},
                )
                if conn.getresponse().status != 200:
                    shed = False
                conn.close()
            except OSError:
                shed = False
        if dripper is not None:
            dripper.close()
        part_ok = True
        if partition:
            from mmlspark_tpu.chaos.conductor import ChaosConductor, Scenario

            ChaosConductor(Scenario.from_spec({"seed": seed, "steps": [
                {"at_s": 0.0, "action": "partition", "links": ["smoke-gw"]},
            ]}), proxies={"smoke-gw": proxy}).run()
            # across an open partition NOTHING comes back — connects
            # still succeed (the proxy accepts), bytes never arrive
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", proxy.port, timeout=2.0
                )
                conn.request(
                    "POST", u.path or "/", json.dumps({"probe": "cut"}),
                    {"Content-Type": "application/json"},
                )
                conn.getresponse()
                part_ok = False  # a reply crossed an open partition
                print("smoke: chaos-wire partition probe LEAKED a reply")
            except OSError:
                pass
            finally:
                conn.close()
            ChaosConductor(Scenario.from_spec({"seed": seed, "steps": [
                {"at_s": 0.0, "action": "heal", "links": ["smoke-gw"]},
            ]}), proxies={"smoke-gw": proxy}).run()
            healed = False
            for _ in range(5):
                try:
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", proxy.port, timeout=5.0
                    )
                    conn.request(
                        "POST", u.path or "/",
                        json.dumps({"probe": "heal"}),
                        {"Content-Type": "application/json"},
                    )
                    if conn.getresponse().status == 200:
                        healed = True
                    conn.close()
                    break
                except OSError:
                    time.sleep(0.5)
            if not healed:
                print("smoke: chaos-wire healed link did not serve")
            part_ok = part_ok and healed
        checker = InvariantChecker(
            gateway_url=url, registry_url=registry_url,
            service_name=service, tolerance=0,
        )
        violations = checker.check(final=True)
        digest = proxy.schedule_digest()[:16]
        passed = ok >= int(0.9 * n) and shed and part_ok and not violations
        print(
            f"smoke: chaos-wire gate — {ok}/{n} ok through the hostile "
            f"link, slowloris shed: {shed}, "
            + (f"partition/heal: {'ok' if part_ok else 'FAILED'}, "
               if partition else "")
            + f"invariants: {'green' if not violations else 'VIOLATED'} "
            f"(schedule {digest}, seed {seed}) — "
            f"{'ok' if passed else 'FAILED'}"
        )
        for v in violations:
            print(f"smoke:   invariant violation: {v}")
        return passed
    finally:
        proxy.stop()


def _verify_tune(url: str, registry_url, service: str,
                 seed: int = 11) -> bool:
    """Tune probe (opt-in, ``--tune``): run a 2-trial ASHA
    micro-experiment against the live fleet — trials are local
    subprocesses reporting through the fleet's registry, the winner is
    published through the epoch-fenced publish plane to ``service``'s
    workers, and the gate requires it to answer a scoring request
    through the gateway (mmlspark_tpu/experiments/;
    docs/experiments.md). Exercises the full tune loop on a deployed
    fleet: CAS rung records on the live registry, artifact replication,
    publication, and gateway routing of a model that did not exist when
    the fleet came up."""
    _ensure_repo_path()
    if not registry_url:
        print("smoke: --tune needs --registry (the controller commits "
              "rung records and publishes through it)", file=sys.stderr)
        return False
    import tempfile

    from mmlspark_tpu.experiments.controller import (
        ExperimentController, ExperimentError,
    )

    stamp = f"{os.getpid()}-{int(time.time())}"
    experiment = f"smoke-tune-{stamp}"
    model = f"smoke-champion-{stamp}"
    ctrl = ExperimentController(
        registry_url, experiment, n_trials=2,
        data="synth:192x6:1", valid="synth:96x6:99",
        min_iters=2, max_iters=4, eta=2, seed=seed,
        workdir=tempfile.mkdtemp(prefix="smoke-tune-"),
        deadline_s=180.0,
        publish_model=model, publish_service=service,
    )
    try:
        out = ctrl.run()
    except ExperimentError as e:
        print(f"smoke: tune probe FAILED ({e})", file=sys.stderr)
        return False
    finally:
        ctrl.close()
    if not out.get("published"):
        print("smoke: tune probe: winner was never published",
              file=sys.stderr)
        return False
    # the freshly published winner must answer through the gateway
    u = urllib.parse.urlsplit(url)
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        conn = http.client.HTTPConnection(
            u.hostname, u.port or 80, timeout=5
        )
        try:
            conn.request(
                "POST", f"/models/{model}",
                body=json.dumps({"features": [0.5] * 6}),
                headers={"Content-Type": "application/json"},
            )
            r = conn.getresponse()
            body = r.read()
            if r.status == 200 and "prediction" in json.loads(body):
                print(
                    f"smoke: tune probe ok — trial "
                    f"{out['winner']['trial']} won, published as "
                    f"{model!r} and scored through the gateway"
                )
                return True
        except (OSError, ValueError):
            pass
        finally:
            conn.close()
        time.sleep(0.3)
    print(f"smoke: tune probe: gateway never answered for {model!r}",
          file=sys.stderr)
    return False


def _verify_no_shared_fs(url: str, registry_url, service: str,
                         deadline_s: float = 90.0) -> bool:
    """No-shared-fs probe (opt-in, ``--no-shared-fs``): prove the fleet
    can serve a model no shared mount ever carried. The probe stands up
    a throwaway content-addressed snapshot on its own artifact ingress
    (advertised through the fleet's registry), then spawns a fresh
    worker process with a private scratch ``--artifact-dir`` and a bare
    ``artifact:vw:<name>@<digest>`` spec — no URL hint and no
    filesystem access to the snapshot. The worker must resolve holders
    purely off the roster, pull the bytes over HTTP (hash-verified,
    resumable; serving/artifacts.py), warm, register under ``service``,
    and answer a scoring request through the gateway
    (docs/robustness.md, docs/artifacts.md)."""
    _ensure_repo_path()
    if not registry_url:
        print("smoke: --no-shared-fs needs --registry (the probe worker "
              "resolves artifact holders off the roster)", file=sys.stderr)
        return False
    import shutil
    import signal
    import subprocess
    import tempfile

    import numpy as np

    from mmlspark_tpu.serving.artifacts import ArtifactServer, ArtifactStore

    stamp = f"{os.getpid()}-{int(time.time())}"
    model = f"smoke-nofs-{stamp}"
    pub_dir = tempfile.mkdtemp(prefix="smoke-nofs-pub-")
    scratch = tempfile.mkdtemp(prefix="smoke-nofs-worker-")
    num_bits = 8
    rng = np.random.default_rng(11)
    snap = os.path.join(pub_dir, f"{model}-v000001.npz")
    meta = {"num_bits": num_bits, "loss": "logistic",
            "no_constant": False, "quantile_tau": 0.5}
    with open(snap, "wb") as f:
        np.savez(
            f,
            weights=rng.standard_normal(1 << num_bits).astype(np.float32),
            meta=json.dumps(meta).encode(),
        )
    store = ArtifactStore(os.path.join(pub_dir, "artifacts"))
    ref = store.put(snap, name=os.path.basename(snap))
    # this process IS the only holder: the worker can only succeed by
    # pulling over HTTP from this ingress, found via the registry
    server = ArtifactServer(
        store, registry_urls=registry_url, service=f"{model}-plane",
        heartbeat_s=1.0,
    )
    server.heartbeat()
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    argv = [
        sys.executable, "-m", "mmlspark_tpu.serving.fleet", "worker",
        "--registry", registry_url, "--service-name", service,
        "--model", "echo", "--host", "127.0.0.1",
        "--load", f"{model}=artifact:vw:{ref.spec}",
        "--artifact-dir", os.path.join(scratch, "cache"),
        "--heartbeat-s", "1", "--drain-s", "5",
    ]
    proc = subprocess.Popen(
        argv, cwd=scratch, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    u = urllib.parse.urlsplit(url)
    probe_row = {"i": [3, 17, 41], "v": [1.0, 0.5, 0.25]}
    ok = False
    try:
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                print(
                    f"smoke: no-shared-fs probe: worker exited rc="
                    f"{proc.returncode} before serving", file=sys.stderr,
                )
                break
            conn = http.client.HTTPConnection(
                u.hostname, u.port or 80, timeout=5
            )
            try:
                conn.request(
                    "POST", f"/models/{model}",
                    body=json.dumps(probe_row),
                    headers={"Content-Type": "application/json"},
                )
                r = conn.getresponse()
                body = r.read()
                if r.status == 200 and "margin" in json.loads(body):
                    ok = True
                    break
            except (OSError, ValueError):
                pass
            finally:
                conn.close()
            time.sleep(0.3)
    finally:
        # SIGTERM = graceful drain: the worker deregisters before dying
        # so the roster heals instead of waiting out the TTL
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=15.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
        server.stop()
        shutil.rmtree(scratch, ignore_errors=True)
        shutil.rmtree(pub_dir, ignore_errors=True)
    if ok:
        print(
            f"smoke: no-shared-fs probe ok — scratch worker pulled "
            f"{model!r} by digest off the roster and scored through "
            "the gateway"
        )
    else:
        print(
            f"smoke: no-shared-fs probe FAILED — gateway never answered "
            f"for {model!r} (digest {ref.digest[:16]}…)", file=sys.stderr,
        )
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="smoke.py", description=__doc__)
    ap.add_argument("url", nargs="?", default="http://127.0.0.1:8080/")
    ap.add_argument("n_requests", nargs="?", type=int, default=None,
                    help="positional alias for --n (back-compat)")
    ap.add_argument("--n", type=int, default=50)
    ap.add_argument(
        "--fault-plan", default=None,
        help="JSON fault plan (inline or file path): chaos-smoke through "
        "the retrying client instead of a bare socket",
    )
    ap.add_argument(
        "--registry", default=None,
        help="driver-registry URL: also scrape every rostered worker's "
        "/metrics and gate on their accepted-request counters",
    )
    ap.add_argument("--service-name", default="serving")
    ap.add_argument(
        "--no-verify-metrics", action="store_true",
        help="skip the /metrics accepted-vs-observed drop gate",
    )
    ap.add_argument(
        "--no-verify-trace", action="store_true",
        help="skip the trace-assembly gate (slowest trace must contain "
        "a gateway hop AND a worker hop)",
    )
    ap.add_argument(
        "--no-verify-throughput", action="store_true",
        help="skip the throughput sanity gate (pipelined keep-alive "
        "requests through the gateway with a box-speed-scaled rps floor)",
    )
    ap.add_argument(
        "--no-verify-profile", action="store_true",
        help="skip the stall-forensics gate (GET /profile answers on the "
        "target and one rostered worker; sampler overhead under bound)",
    )
    ap.add_argument(
        "--swap", action="store_true",
        help="hot-swap drill: load a new model version on every backend "
        "and swap it in while the request phase runs; the gate then "
        "requires zero drops ACROSS the flip",
    )
    ap.add_argument("--swap-model", default="echo",
                    help="model name to swap (default: echo)")
    ap.add_argument("--swap-spec", default="echo",
                    help="spec to load as the new version (default: echo)")
    ap.add_argument(
        "--chaos-wire", action="store_true",
        help="opt-in hostile-wire gate: run a short seeded wire-fault "
        "schedule (latency+jitter, throttle, slowloris) through a chaos "
        "proxy fronting the gateway and require the fleet-wide "
        "invariant checker green (mmlspark_tpu/chaos/; docs/chaos.md)",
    )
    ap.add_argument(
        "--chaos-wire-seed", type=int, default=7,
        help="seed for the --chaos-wire schedule (same seed => "
        "byte-identical fault schedule)",
    )
    ap.add_argument(
        "--tune", action="store_true",
        help="opt-in tune probe: 2-trial ASHA micro-experiment against "
        "the live fleet's registry (trials run as local subprocesses), "
        "winner published through the epoch-fenced publish plane and "
        "required to answer through the gateway (needs --registry; "
        "mmlspark_tpu/experiments/; docs/experiments.md)",
    )
    ap.add_argument(
        "--no-shared-fs", action="store_true",
        help="opt-in no-shared-fs probe: spawn a scratch worker with no "
        "filesystem access to any snapshot dir; it must pull a published "
        "model by bare digest off the registry roster and answer through "
        "the gateway (needs --registry; docs/robustness.md, "
        "docs/artifacts.md)",
    )
    ap.add_argument(
        "--chaos-wire-partition", action="store_true",
        help="with --chaos-wire: also run a conductor-driven "
        "partition/heal probe on the chaos link (blackholed link must "
        "pass nothing, healed link must serve again)",
    )
    args = ap.parse_args(argv)
    n = args.n_requests if args.n_requests is not None else args.n
    verify = not args.no_verify_metrics
    before = (
        _fleet_counters(args.url, args.registry, args.service_name)
        if verify else None
    )
    extra_gw = extra_workers = 0
    swap_ok = True
    if args.swap and args.fault_plan:
        # the drill's whole point is the strict forwarded==successes
        # equality across the flip; a fault plan relaxes that gate to >=
        # and the drill's raw client wouldn't retry through it anyway
        print("smoke: --swap and --fault-plan are mutually exclusive "
              "(run the chaos smoke and the swap drill separately)",
              file=sys.stderr)
        return 2
    plan = None
    faults_before = _count_fault_records() if args.fault_plan else 0
    if args.swap:
        ok, lat, swap_ok, extra_gw, extra_workers = _swap_drill(
            args.url, n, args.registry, args.service_name,
            args.swap_model, args.swap_spec,
        )
    elif args.fault_plan:
        ok, lat, plan = _smoke_chaos(args.url, n, args.fault_plan)
    else:
        ok, lat = _smoke_raw(urllib.parse.urlparse(args.url), n)
    lat.sort()
    p50 = lat[len(lat) // 2]
    print(f"smoke: {ok}/{n} ok, p50 {p50:.2f} ms")
    metrics_ok = True
    if verify:
        after = _fleet_counters(args.url, args.registry, args.service_name)
        metrics_ok = _verify_metrics(
            before, after, ok, chaos=bool(args.fault_plan),
            extra_gw=extra_gw, extra_workers=extra_workers,
        )
        metrics_ok = _verify_slo(args.url) and metrics_ok
        metrics_ok = _verify_containment(before, after, plan) and metrics_ok
        metrics_ok = _verify_freshness(
            args.url, args.registry, args.service_name
        ) and metrics_ok
    throughput_ok = True
    if not args.no_verify_throughput and not args.fault_plan:
        # chaos smokes measure fault handling, not clean-path rps — an
        # armed fault plan would fail the floor by design
        throughput_ok = _verify_throughput(args.url)
    trace_ok = True
    if not args.no_verify_trace:
        trace_ok = _verify_trace(args.url, args.registry, args.service_name)
    profile_ok = True
    if not args.no_verify_profile:
        profile_ok = _verify_profile(
            args.url, args.registry, args.service_name
        )
    flight_ok = True
    if plan is not None:
        flight_ok = _verify_flightrec(plan, faults_before)
    chaos_wire_ok = True
    if args.chaos_wire:
        # AFTER the counter gates: the proxy's extra traffic lands on
        # the fleet's counters, and the invariant checker judges the
        # totals on its own terms
        chaos_wire_ok = _verify_chaos_wire(
            args.url, args.registry, args.service_name,
            seed=args.chaos_wire_seed,
            partition=args.chaos_wire_partition,
        )
    tune_ok = True
    if args.tune:
        # LAST: the probe's winner publication shifts worker model
        # inventory and its scoring traffic would skew every counter
        # gate above
        tune_ok = _verify_tune(args.url, args.registry, args.service_name)
    no_shared_fs_ok = True
    if args.no_shared_fs:
        # also after the counter gates: the probe worker joins (then
        # gracefully leaves) the roster, which would shift the worker
        # inventory the gates above compare against
        no_shared_fs_ok = _verify_no_shared_fs(
            args.url, args.registry, args.service_name
        )
    return 0 if (
        ok == n and metrics_ok and swap_ok and trace_ok and flight_ok
        and throughput_ok and chaos_wire_ok and tune_ok and profile_ok
        and no_shared_fs_ok
    ) else 1


if __name__ == "__main__":
    raise SystemExit(main())
