"""Fleet smoke test: POST through the gateway, check replies + p50.

    python tools/deploy/smoke.py http://localhost:8080/ [n_requests]
"""

import http.client
import json
import sys
import time
import urllib.parse


def main() -> int:
    url = sys.argv[1] if len(sys.argv) > 1 else "http://127.0.0.1:8080/"
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 50
    u = urllib.parse.urlparse(url)
    conn = http.client.HTTPConnection(u.hostname, u.port or 80, timeout=10)
    lat = []
    ok = 0
    for i in range(n):
        body = json.dumps({"x": i})
        t0 = time.perf_counter()
        conn.request("POST", u.path or "/", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        lat.append((time.perf_counter() - t0) * 1e3)
        if resp.status == 200 and json.loads(data).get("echo", {}).get("x") == i:
            ok += 1
    conn.close()
    lat.sort()
    p50 = lat[len(lat) // 2]
    print(f"smoke: {ok}/{n} ok, p50 {p50:.2f} ms")
    return 0 if ok == n else 1


if __name__ == "__main__":
    raise SystemExit(main())
