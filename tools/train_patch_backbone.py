"""Train the natural-image zoo backbone from committed data.

The reference's zoo ships backbones trained on natural images
(downloader/ModelDownloader.scala:210-276); this egress-free build trains
its own: a width-32 ResNet-18 pretrained SELF-SUPERVISED on 32x32 patches
of the two natural photographs that ship with scikit-learn
(``sklearn.datasets.load_sample_images``: 'china.jpg', 'flower.jpg') using
rotation prediction (RotNet, Gidaris et al. 2018) — predicting which of
{0, 90, 180, 270} degrees a patch was rotated forces the network to learn
real visual structure (edges, orientation, texture, layout), which is what
makes the features TRANSFER.

Holdout discipline: training patches come only from the LEFT 75% of each
photo; the right strip is never seen, and the transfer gate
(tests/test_zoo_weights.py) probes features there.

Reproduce:  PYTHONPATH=. python tools/train_patch_backbone.py
            (uses the default JAX backend: a TPU finishes in ~2 min; on
            CPU expect ~30 min. Deterministic given the fixed seed.)
The checkpoint is stored float16 (~5.6 MB) and restored to f32 by
ModelDownloader.load.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

SEED = 11
PATCH = 32
N_PATCHES = 40_960
BATCH = 512
EPOCHS = 12
WIDTH = 32          # ResNet-18 at num_filters=32: ~2.8M params
TRAIN_FRACTION = 0.75  # left fraction of each photo used for training


def sample_patches(rng: np.ndarray, n: int, train_region: bool = True) -> np.ndarray:
    """(n, PATCH, PATCH, 3) uint8 patches from the committed photos."""
    from sklearn.datasets import load_sample_images

    images = load_sample_images().images  # [china, flower], (427, 640, 3) u8
    out = np.empty((n, PATCH, PATCH, 3), np.uint8)
    for i in range(n):
        img = images[int(rng.integers(2))]
        h, w = img.shape[:2]
        cut = int(w * TRAIN_FRACTION)
        if train_region:
            x0 = int(rng.integers(0, cut - PATCH))
        else:
            x0 = int(rng.integers(cut, w - PATCH))
        y0 = int(rng.integers(0, h - PATCH))
        out[i] = img[y0: y0 + PATCH, x0: x0 + PATCH]
    return out


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    from mmlspark_tpu.downloader.zoo import ModelDownloader, ModelSchema
    from mmlspark_tpu.models.resnet import resnet18
    from mmlspark_tpu.ops.image import normalize

    rng = np.random.default_rng(SEED)
    patches = sample_patches(rng, N_PATCHES)
    rot = rng.integers(0, 4, N_PATCHES)
    x = np.stack([np.rot90(p, k) for p, k in zip(patches, rot)])
    y = rot.astype(np.int32)
    n_val = 2048
    xtr, ytr = x[:-n_val], y[:-n_val]
    xva, yva = x[-n_val:], y[-n_val:]

    model = resnet18(num_classes=4, small_inputs=True, num_filters=WIDTH)
    variables = model.init(
        jax.random.PRNGKey(SEED),
        jnp.zeros((1, PATCH, PATCH, 3), jnp.float32), train=True,
    )
    params, batch_stats = variables["params"], variables["batch_stats"]
    steps_per_epoch = len(xtr) // BATCH
    tx = optax.adamw(
        optax.cosine_decay_schedule(3e-3, EPOCHS * steps_per_epoch),
        weight_decay=1e-4,
    )
    opt_state = tx.init(params)

    def one_step(carry, idx):
        params, batch_stats, opt_state = carry
        xb = normalize(xtr_dev[idx].astype(jnp.float32))
        yb = ytr_dev[idx]

        def loss_fn(p):
            out, mut = model.apply(
                {"params": p, "batch_stats": batch_stats},
                xb, train=True, mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                out["logits"], yb
            ).mean()
            return loss, mut["batch_stats"]

        (loss, batch_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, batch_stats, opt_state), loss

    # whole epoch = ONE dispatch (lax.scan over shuffled minibatches): the
    # same fusion pattern as the GBDT trainer — essential over a relay
    @jax.jit
    def run_epoch(params, batch_stats, opt_state, key):
        perm = jax.random.permutation(key, len(xtr))[: steps_per_epoch * BATCH]
        idxs = perm.reshape(steps_per_epoch, BATCH)
        (params, batch_stats, opt_state), losses = jax.lax.scan(
            one_step, (params, batch_stats, opt_state), idxs
        )
        return params, batch_stats, opt_state, losses.mean()

    @jax.jit
    def accuracy(params, batch_stats, xb, yb):
        out = model.apply(
            {"params": params, "batch_stats": batch_stats},
            normalize(xb.astype(jnp.float32)), train=False,
        )
        return (out["logits"].argmax(-1) == yb).mean()

    xtr_dev = jax.device_put(jnp.asarray(xtr))
    ytr_dev = jax.device_put(jnp.asarray(ytr))
    xva_dev, yva_dev = jnp.asarray(xva), jnp.asarray(yva)
    for epoch in range(EPOCHS):
        t0 = time.time()
        params, batch_stats, opt_state, loss = run_epoch(
            params, batch_stats, opt_state, jax.random.PRNGKey(1000 + epoch)
        )
        acc = float(accuracy(params, batch_stats, xva_dev, yva_dev))
        print(
            f"epoch {epoch}: loss {float(loss):.4f} "
            f"rot-acc {acc:.4f} ({time.time() - t0:.1f}s)", flush=True,
        )
    assert acc > 0.75, f"rotation pretraining failed to learn (acc={acc})"

    to_np16 = lambda t: np.asarray(t, np.float16)  # noqa: E731
    variables = {
        "params": jax.tree_util.tree_map(to_np16, params),
        "batch_stats": jax.tree_util.tree_map(to_np16, batch_stats),
    }
    from mmlspark_tpu.downloader.zoo import PACKAGED_DIR

    schema = ModelSchema(
        name="ResNet18_Patches",
        variant="ResNet18",
        num_classes=4,
        image_size=PATCH,
        small_inputs=True,
        num_filters=WIDTH,
        seed=SEED,
    )
    dl = ModelDownloader(repo_dir=PACKAGED_DIR)
    dl.register(schema, variables)
    print("packaged", os.path.join(PACKAGED_DIR, "ResNet18_Patches.msgpack"))


if __name__ == "__main__":
    main()
